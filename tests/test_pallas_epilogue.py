"""Fused IN -> ReLU -> reflect-pad epilogue kernel vs the XLA reference
composition (reflect_pad . relu . instance_norm) — forward and backward,
interpret mode on CPU (the driver/bench exercise the compiled TPU path).

Also pins the dtype-aware VMEM eligibility boundary and the dispatch
fallback: ineligible shapes must silently get the XLA composition with
identical semantics.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cyclegan_tpu.ops.norm import _instance_norm_xla, instance_norm_relu_pad
from cyclegan_tpu.ops.padding import reflect_pad
from cyclegan_tpu.ops.pallas import vmem
from cyclegan_tpu.ops.pallas.epilogue_kernel import (
    epilogue_eligible,
    instance_norm_relu_pad_pallas,
)


def _rand(shape, seed=0, dtype=jnp.float32):
    k = jax.random.PRNGKey(seed)
    return (jax.random.normal(k, shape) * 2 + 0.5).astype(dtype)


def _reference(x, scale, bias, pad, eps=1e-3):
    return reflect_pad(jax.nn.relu(_instance_norm_xla(x, scale, bias, eps)), pad)


# Shapes chosen to hit the cases that break naive reflection code:
# batches > 1, non-square H != W (axis mix-ups), pad=3 (multi-row
# mirror bands), odd extents (edge taps land off the tile boundary),
# and channel counts below/at the 128-lane tile.
SHAPES = [
    ((2, 8, 8, 128), 1),
    ((1, 16, 16, 64), 1),
    ((1, 6, 10, 32), 1),
    ((2, 5, 7, 16), 1),
    ((1, 8, 8, 128), 3),
    ((2, 7, 9, 8), 3),
]


@pytest.mark.parametrize("shape,pad", SHAPES)
def test_epilogue_forward_matches_reference(shape, pad):
    c = shape[-1]
    x = _rand(shape)
    scale = _rand((c,), 1)
    bias = _rand((c,), 2)
    got = instance_norm_relu_pad_pallas(x, scale, bias, pad=pad, interpret=True)
    want = _reference(x, scale, bias, pad)
    assert got.shape == want.shape
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
    )


def test_epilogue_padded_border_is_exact_reflection():
    """The mirror bands must satisfy tf.pad REFLECT exactly: pad offset
    d equals interior offset d, the border row/col itself never
    repeated."""
    x = _rand((1, 6, 7, 8), 3)
    scale = _rand((8,), 1)
    bias = _rand((8,), 2)
    pad = 2
    y = np.asarray(
        instance_norm_relu_pad_pallas(x, scale, bias, pad=pad, interpret=True)
    )
    core = y[:, pad:-pad, pad:-pad, :]
    np.testing.assert_array_equal(
        y, np.pad(core, ((0, 0), (pad, pad), (pad, pad), (0, 0)),
                  mode="reflect")
    )


@pytest.mark.parametrize("shape,pad", SHAPES)
def test_epilogue_backward_matches_reference(shape, pad):
    c = shape[-1]
    x = _rand(shape)
    scale = _rand((c,), 1)
    bias = _rand((c,), 2)

    def loss_pallas(x, s, b):
        y = instance_norm_relu_pad_pallas(x, s, b, pad=pad, interpret=True)
        return jnp.sum(jnp.sin(y) * y)

    def loss_ref(x, s, b):
        y = _reference(x, s, b, pad)
        return jnp.sum(jnp.sin(y) * y)

    g_p = jax.grad(loss_pallas, argnums=(0, 1, 2))(x, scale, bias)
    g_r = jax.grad(loss_ref, argnums=(0, 1, 2))(x, scale, bias)
    for a, b_, name in zip(g_p, g_r, ["dx", "dscale", "dbias"]):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), rtol=2e-4, atol=5e-5, err_msg=name
        )


def test_epilogue_bfloat16_forward_and_backward():
    shape, pad = (2, 8, 8, 64), 1
    x = _rand(shape, dtype=jnp.bfloat16)
    scale = _rand((64,), 1)
    bias = _rand((64,), 2)
    got = instance_norm_relu_pad_pallas(x, scale, bias, pad=pad, interpret=True)
    assert got.dtype == jnp.bfloat16
    want = _reference(x, scale, bias, pad)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=1e-2, atol=1e-2,
    )

    def loss(fn):
        def inner(x, s, b):
            y = fn(x, s, b)
            return jnp.sum(y.astype(jnp.float32) ** 2)
        return inner

    g_p = jax.grad(
        loss(lambda x, s, b: instance_norm_relu_pad_pallas(
            x, s, b, pad=pad, interpret=True)), argnums=(0, 1, 2)
    )(x, scale, bias)
    g_r = jax.grad(
        loss(lambda x, s, b: _reference(x, s, b, pad)), argnums=(0, 1, 2)
    )(x, scale, bias)
    for a, b_, name in zip(g_p, g_r, ["dx", "dscale", "dbias"]):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b_, np.float32),
            rtol=1e-2, atol=1e-2, err_msg=name,
        )


# --------------------------------------------------- eligibility gate


def test_eligibility_is_dtype_aware():
    # generator trunk at 256^2: eligible for BOTH dtypes
    assert epilogue_eligible((1, 64, 64, 256), jnp.float32, 1)
    assert epilogue_eligible((1, 64, 64, 256), jnp.bfloat16, 1)
    # the boundary: 96x96 f32 blows the budget, bf16 halves it and fits
    assert not epilogue_eligible((1, 96, 96, 128), jnp.float32, 1)
    assert epilogue_eligible((1, 96, 96, 128), jnp.bfloat16, 1)
    # outermost generator layer at 256^2: ineligible either way
    assert not epilogue_eligible((1, 256, 256, 64), jnp.float32, 3)
    assert not epilogue_eligible((1, 256, 256, 64), jnp.bfloat16, 3)
    # reflection needs pad < min(H, W)
    assert not epilogue_eligible((1, 3, 64, 8), jnp.float32, 3)
    assert not epilogue_eligible((1, 64, 64), jnp.float32, 1)  # not 4-D


def test_vmem_budget_accounting():
    # the backward's three slabs (x + padded g + dx) gate eligibility
    h = w = 64
    assert vmem.epilogue_bytes(h, w, 1, 4) == (
        (2 * h * w + (h + 2) * (w + 2)) * vmem.C_BLK * 4
    )
    # dtype-aware norm bounds: f32 keeps the historical 8192 limit,
    # bf16 doubles it (the satellite fix: 4 B/element was assumed
    # unconditionally)
    assert vmem.norm_fwd_max_hw(4) == 8192
    assert vmem.norm_fwd_max_hw(2) == 16384
    # backward budgets agree with forward for every itemsize, so a
    # Pallas-forward shape never falls back in the backward
    for itemsize in (2, 4):
        assert vmem.norm_bwd_max_hw(itemsize) == vmem.norm_fwd_max_hw(itemsize)


def test_ineligible_shape_raises():
    x = _rand((1, 128, 128, 8))
    with pytest.raises(NotImplementedError):
        instance_norm_relu_pad_pallas(
            x, jnp.ones(8), jnp.zeros(8), pad=1, interpret=True
        )


# ----------------------------------------------------------- dispatch


def test_dispatch_uses_xla_fallback_on_ineligible_shape():
    """instance_norm_relu_pad on a shape past the slab budget must
    return the XLA composition (same semantics), not raise."""
    x = _rand((1, 128, 128, 8))  # hw=16384: past the f32 budget
    scale = _rand((8,), 1)
    bias = _rand((8,), 2)
    got = instance_norm_relu_pad(x, scale, bias, pad=1)
    want = _reference(x, scale, bias, 1)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-6, atol=1e-6
    )


def test_dispatch_impl_xla_skips_the_kernel():
    x = _rand((1, 8, 8, 16))
    scale = _rand((16,), 1)
    bias = _rand((16,), 2)
    got = instance_norm_relu_pad(x, scale, bias, pad=1, impl="xla")
    want = _reference(x, scale, bias, 1)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-6, atol=1e-6
    )


@pytest.mark.parametrize("impl", ["auto", "pallas"])
def test_dispatch_eligible_shape_matches_reference(impl):
    x = _rand((2, 8, 8, 32))
    scale = _rand((32,), 1)
    bias = _rand((32,), 2)
    got = instance_norm_relu_pad(x, scale, bias, pad=1, impl=impl)
    want = _reference(x, scale, bias, 1)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
    )


def test_dispatch_grad_through_fallback_boundary():
    """Gradients must flow through BOTH dispatch arms with the same
    math: one shape served by the kernel, one by the composition."""
    scale = _rand((8,), 1)
    bias = _rand((8,), 2)
    for shape in [(1, 8, 8, 8), (1, 128, 128, 8)]:
        x = _rand(shape)

        def loss(x, s, b):
            return jnp.sum(instance_norm_relu_pad(x, s, b, pad=1) ** 2)

        def loss_ref(x, s, b):
            return jnp.sum(_reference(x, s, b, 1) ** 2)

        g = jax.grad(loss, argnums=(0, 1, 2))(x, scale, bias)
        g_r = jax.grad(loss_ref, argnums=(0, 1, 2))(x, scale, bias)
        for a, b_ in zip(g, g_r):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b_), rtol=2e-4, atol=5e-5
            )
