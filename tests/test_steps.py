"""Train/test-step tests (SURVEY.md §4): losses finite, all four param
trees update, disc updates don't touch gen params, and — the crux — the
fused single-backward combined-scalar gradient exactly matches the
reference's four independent tape gradients (main.py:207-262)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cyclegan_tpu import losses
from cyclegan_tpu.train import (
    create_state,
    build_models,
    make_cycle_step,
    make_test_step,
    make_train_step,
)


@pytest.fixture(scope="module")
def setup(tiny_config):
    cfg = tiny_config
    state = create_state(cfg, jax.random.PRNGKey(0))
    kx, ky = jax.random.split(jax.random.PRNGKey(42))
    n = 2
    x = jax.random.uniform(kx, (n, cfg.model.image_size, cfg.model.image_size, 3), minval=-1, maxval=1)
    y = jax.random.uniform(ky, (n, cfg.model.image_size, cfg.model.image_size, 3), minval=-1, maxval=1)
    w = jnp.ones((n,), jnp.float32)
    return cfg, state, x, y, w


def reference_style_grads(cfg, state, x, y, w, gbs):
    """Four separate per-network gradients exactly as the reference's
    persistent tape + per-var_list minimize computes them
    (main.py:209-260) — the slow-but-obviously-correct oracle."""
    gen, disc = build_models(cfg)
    lam_c, lam_i = cfg.loss.lambda_cycle, cfg.loss.lambda_identity

    def g_total(g_params):
        fake_y = gen.apply(g_params, x)
        fake_x = gen.apply(state.f_params, y)
        adv = losses.generator_loss(disc.apply(state.dy_params, fake_y), w, gbs)
        cyc = losses.cycle_loss(y, gen.apply(g_params, fake_x), w, gbs, lam_c)
        ident = losses.identity_loss(y, gen.apply(g_params, y), w, gbs, lam_i)
        return adv + cyc + ident

    def f_total(f_params):
        fake_y = gen.apply(state.g_params, x)
        fake_x = gen.apply(f_params, y)
        adv = losses.generator_loss(disc.apply(state.dx_params, fake_x), w, gbs)
        cyc = losses.cycle_loss(x, gen.apply(f_params, fake_y), w, gbs, lam_c)
        ident = losses.identity_loss(x, gen.apply(f_params, x), w, gbs, lam_i)
        return adv + cyc + ident

    def x_loss(dx_params):
        fake_x = gen.apply(state.f_params, y)
        return losses.discriminator_loss(
            disc.apply(dx_params, x), disc.apply(dx_params, fake_x), w, gbs
        )

    def y_loss(dy_params):
        fake_y = gen.apply(state.g_params, x)
        return losses.discriminator_loss(
            disc.apply(dy_params, y), disc.apply(dy_params, fake_y), w, gbs
        )

    return (
        jax.grad(g_total)(state.g_params),
        jax.grad(f_total)(state.f_params),
        jax.grad(x_loss)(state.dx_params),
        jax.grad(y_loss)(state.dy_params),
    )


def test_fused_gradients_match_reference_semantics(setup):
    cfg, state, x, y, w = setup
    gbs = x.shape[0]
    # Recover the fused step's gradients by re-deriving them through the
    # same combined loss the train step uses.
    from cyclegan_tpu.train.steps import make_train_step as _  # noqa
    import cyclegan_tpu.train.steps as steps_mod

    gen, disc = build_models(cfg)
    train_step = make_train_step(cfg, gbs)

    # Build the combined loss exactly as the step factory does, via the
    # private grad path: run one step with SGD-like introspection instead —
    # simpler: recompute via jax.grad of the factory's combined_loss by
    # reaching through a fresh factory.
    lam_c, lam_i = cfg.loss.lambda_cycle, cfg.loss.lambda_identity
    stop = jax.lax.stop_gradient

    def combined(g_params, f_params, dx_params, dy_params):
        fake_y = gen.apply(g_params, x)
        fake_x = gen.apply(f_params, y)
        g_adv = losses.generator_loss(disc.apply(stop(dy_params), fake_y), w, gbs)
        f_adv = losses.generator_loss(disc.apply(stop(dx_params), fake_x), w, gbs)
        g_cyc = losses.cycle_loss(y, gen.apply(g_params, stop(fake_x)), w, gbs, lam_c)
        f_cyc = losses.cycle_loss(x, gen.apply(f_params, stop(fake_y)), w, gbs, lam_c)
        g_id = losses.identity_loss(y, gen.apply(g_params, y), w, gbs, lam_i)
        f_id = losses.identity_loss(x, gen.apply(f_params, x), w, gbs, lam_i)
        x_l = losses.discriminator_loss(
            disc.apply(dx_params, x), disc.apply(dx_params, stop(fake_x)), w, gbs
        )
        y_l = losses.discriminator_loss(
            disc.apply(dy_params, y), disc.apply(dy_params, stop(fake_y)), w, gbs
        )
        return g_adv + g_cyc + g_id + f_adv + f_cyc + f_id + x_l + y_l

    fused = jax.grad(combined, argnums=(0, 1, 2, 3))(
        state.g_params, state.f_params, state.dx_params, state.dy_params
    )
    oracle = reference_style_grads(cfg, state, x, y, w, gbs)
    for got_tree, want_tree, name in zip(fused, oracle, ["G", "F", "dX", "dY"]):
        flat_got = jax.tree.leaves(got_tree)
        flat_want = jax.tree.leaves(want_tree)
        for g_leaf, w_leaf in zip(flat_got, flat_want):
            np.testing.assert_allclose(
                np.asarray(g_leaf), np.asarray(w_leaf), rtol=1e-4, atol=1e-6,
                err_msg=f"gradient mismatch for network {name}",
            )


def test_train_step_updates_all_four_trees(setup):
    cfg, state, x, y, w = setup
    train_step = jax.jit(make_train_step(cfg, x.shape[0]))
    new_state, metrics = train_step(state, x, y, w)
    assert int(new_state.step) == 1
    for name in ["g_params", "f_params", "dx_params", "dy_params"]:
        before = jax.tree.leaves(getattr(state, name))
        after = jax.tree.leaves(getattr(new_state, name))
        changed = any(
            not np.allclose(np.asarray(b), np.asarray(a)) for b, a in zip(before, after)
        )
        assert changed, f"{name} did not update"
    for k, v in metrics.items():
        assert np.isfinite(float(v)), f"metric {k} not finite"


def test_train_step_metric_keys_match_reference(setup):
    cfg, state, x, y, w = setup
    reference = {
        "loss_G/loss", "loss_G/cycle", "loss_G/identity", "loss_G/total",
        "loss_F/loss", "loss_F/cycle", "loss_F/identity", "loss_F/total",
        "loss_X/loss", "loss_Y/loss",
    }
    train_step = jax.jit(make_train_step(cfg, x.shape[0]))
    _, metrics = train_step(state, x, y, w)
    # The reference set survives verbatim; the health layer (on by
    # default, obs/health.py) adds only namespaced health/* keys on top
    # (exact-set pin: tests/test_health.py).
    assert reference <= set(metrics)
    assert all(k in reference or k.startswith("health/") for k in metrics)


def test_test_step_metrics(setup):
    cfg, state, x, y, w = setup
    test_step = jax.jit(make_test_step(cfg, x.shape[0]))
    metrics = test_step(state, x, y, w)
    for extra in [
        "error/MAE(X, F(G(X)))", "error/MAE(Y, G(F(Y)))",
        "error/MAE(X, F(X))", "error/MAE(Y, G(Y))",
    ]:
        assert extra in metrics
        assert np.isfinite(float(metrics[extra]))


def test_cycle_step_shapes(setup):
    cfg, state, x, y, _ = setup
    cycle_step = jax.jit(make_cycle_step(cfg))
    fake_x, fake_y, cycle_x, cycle_y = cycle_step(state, x, y)
    for t in (fake_x, fake_y, cycle_x, cycle_y):
        assert t.shape == x.shape


def test_padded_batch_equals_unpadded(setup):
    """A zero-padded masked batch must produce the same losses and updates
    as the raw ragged batch at the same global_batch_size (the TPU-native
    replacement for the reference's remainder batches, main.py:32-33)."""
    cfg, state, x, y, _ = setup
    gbs = 2
    # Ragged: only 1 real sample, global batch 2 (as in a final batch).
    x1, y1 = x[:1], y[:1]
    w1 = jnp.ones((1,), jnp.float32)
    step_ragged = jax.jit(make_test_step(cfg, gbs))
    m_ragged = step_ragged(state, x1, y1, w1)
    # Padded to 2 with zeros + mask.
    xp = jnp.concatenate([x1, jnp.zeros_like(x1)])
    yp = jnp.concatenate([y1, jnp.zeros_like(y1)])
    wp = jnp.asarray([1.0, 0.0])
    step_padded = jax.jit(make_test_step(cfg, gbs))
    m_padded = step_padded(state, xp, yp, wp)
    for k in m_ragged:
        np.testing.assert_allclose(
            float(m_ragged[k]), float(m_padded[k]), rtol=1e-5, atol=1e-6, err_msg=k
        )
