"""graftlint: the static-discipline framework (tools/graftlint).

Covers, per ISSUE 11's acceptance bar:

- the donation-aliasing dataflow rule flags BOTH historical bug shapes
  in tests/data/lint_corpus (the PR-8 restore-then-donate and the PR-10
  device_put-alias variants) and passes both post-fix shapes clean;
- the no-sync rule keeps check_no_sync.py's exact verdict semantics
  while fixing its string-literal false-positive and aliased-import
  false-negative classes (and the wrapper stays byte-compatible);
- tracer-leak catches host control flow / concretization on traced
  values, exempts static inspections and static args, and warns on
  jit-in-loop retrace hazards and unhashable static literals;
- the compile-site census recognizes construction sites semantically
  (lower_forward().compile() yes, re.compile/str.lower no) and the
  newest committed docs/compile_sites_r*.json matches a fresh scan on
  the line-independent keys;
- suppressions require a reason; the baseline grandfathers one finding
  per entry and stale entries never fail;
- the whole repo is ZERO unsuppressed findings under the committed
  baseline — the self-application gate the preflight enforces.

Pure stdlib + AST: no jax import, no devices, fast enough for tier-1.
"""

import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
sys.path.insert(0, REPO)

from graftlint import engine  # noqa: E402
from graftlint.rules import ALL_RULES, make_rules  # noqa: E402
from graftlint.rules.census import CompileSiteCensusRule, site_key  # noqa: E402
from graftlint.rules.donation import DonationAliasingRule  # noqa: E402
from graftlint.rules import nosync  # noqa: E402
from graftlint.rules.tracer import TracerLeakRule  # noqa: E402

CORPUS = os.path.join("tests", "data", "lint_corpus")


def lint_file(rel, rules, repo=REPO, baseline=None):
    return engine.run(repo, rules, files=[rel], baseline=baseline)


def lint_source(tmp_path, source, rules, baseline=None):
    (tmp_path / "mod.py").write_text(source)
    return engine.run(str(tmp_path), rules, files=["mod.py"],
                      baseline=baseline)


# ------------------------------------------- donation-aliasing: the corpus


@pytest.mark.parametrize("fixture, origin_hint", [
    ("pr8_rebuffer_bug.py", "checkpoint restore"),
    ("pr10_elastic_bug.py", "device_put of host gather"),
])
def test_corpus_bug_shapes_flagged(fixture, origin_hint):
    """Both historical heap-corruption shapes (the PR-8 restore-then-
    donate and the PR-10 reshard alias) are errors."""
    res = lint_file(os.path.join(CORPUS, fixture),
                    [DonationAliasingRule()])
    assert len(res.findings) == 1, [f.render() for f in res.findings]
    f = res.findings[0]
    assert f.rule == "donation-aliasing"
    assert f.severity == "error"
    assert origin_hint in f.message
    assert "donate" in f.message


@pytest.mark.parametrize("fixture", [
    "pr8_rebuffer_fixed.py",
    "pr10_elastic_fixed.py",
])
def test_corpus_fixed_shapes_clean(fixture):
    """The sanctioned re-buffering (checkpoint._rebuffer / jnp.copy)
    launders the taint: post-fix shapes analyze clean."""
    res = lint_file(os.path.join(CORPUS, fixture),
                    [DonationAliasingRule()])
    assert res.findings == [], [f.render() for f in res.findings]


def test_donation_unknown_call_launders(tmp_path):
    """Precision over recall: a value that passes through an unknown
    call is no longer assumed aliased (no cascade of false positives)."""
    res = lint_source(tmp_path, (
        "import jax\n"
        "def f(ckptr, slot, abstract, step_fn, x):\n"
        "    state = ckptr.restore(slot, abstract)\n"
        "    state = step_fn(state)\n"   # unknown call -> launders
        "    step = jax.jit(step_fn, donate_argnums=(0,))\n"
        "    return step(state, x)\n"
    ), [DonationAliasingRule()])
    assert res.findings == []


# ---------------------------------------------------------------- no-sync


def test_nosync_aliased_import_caught():
    """`from jax import device_get as g` — the token scanner's
    false-negative class — is resolved and flagged at the use site."""
    src = ("from jax import device_get as g\n"
           "def f(x):\n"
           "    return g(x)\n")
    hits = nosync.scan_source(src, allow_sanctioned=True)
    assert any(line == 3 and tok == "device_get"
               for line, tok, _ in hits), hits


def test_nosync_strings_and_comments_clean():
    """The false-positive class: names inside string literals and
    comments never violate."""
    src = ('msg = "never call block_until_ready or jax.device_get"\n'
           "# block_until_ready would be a sync here\n")
    assert nosync.scan_source(src, allow_sanctioned=True) == []
    assert nosync.scan_source(src, allow_sanctioned=False) == []


def test_nosync_sanction_policy():
    src = ("import jax\n"
           "h = jax.device_get(x)  # sanctioned-fetch: drain\n")
    assert nosync.scan_source(src, allow_sanctioned=True) == []
    hits = nosync.scan_source(src, allow_sanctioned=False)
    assert len(hits) == 1
    assert "no sanctioned sites exist in obs/" in hits[0][2]


def test_nosync_wrapper_messages_byte_compatible(tmp_path):
    """The check_no_sync.py wrapper emits the historical message
    formats (the strings downstream tooling and the runbook quote)."""
    from check_no_sync import check_file

    bad = tmp_path / "bad.py"
    bad.write_text("import jax\n"
                   "x.block_until_ready()\n"
                   "jax.device_get(x)\n")
    v = check_file(str(bad), allow_sanctioned=True)
    assert v == [
        f"{bad}:2: forbidden sync `block_until_ready` in the hot path",
        f"{bad}:3: `device_get` outside the sanctioned fetch window "
        f"(missing `# sanctioned-fetch` marker)",
    ]


def test_nosync_repo_hot_path_clean_via_rule():
    """The rule form agrees with the wrapper: the repo's hot path is
    clean through the graftlint engine too."""
    res = engine.run(REPO, make_rules(["no-sync"]))
    assert res.findings == [], [f.render() for f in res.findings]


# ------------------------------------------------------------ tracer-leak


def test_tracer_if_on_traced_value(tmp_path):
    res = lint_source(tmp_path, (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    if x > 0:\n"
        "        return x\n"
        "    return -x\n"
    ), [TracerLeakRule()])
    assert len(res.findings) == 1
    assert "host control flow" in res.findings[0].message
    assert res.findings[0].severity == "error"


def test_tracer_cast_and_item(tmp_path):
    res = lint_source(tmp_path, (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    a = float(x)\n"
        "    b = x.sum().item()\n"
        "    return a + b\n"
    ), [TracerLeakRule()])
    msgs = sorted(f.message for f in res.findings)
    assert len(msgs) == 2, msgs
    assert any("float()" in m for m in msgs)
    assert any(".item()" in m for m in msgs)


def test_tracer_static_inspections_exempt(tmp_path):
    """shape/ndim/dtype access, len(), and `is None` checks stay
    host-side by construction — no findings."""
    res = lint_source(tmp_path, (
        "import jax\n"
        "@jax.jit\n"
        "def f(x, mask=None):\n"
        "    if x.shape[0] > 2 and x.ndim == 4:\n"
        "        x = x * 2\n"
        "    if mask is not None:\n"
        "        x = x + mask\n"
        "    n = len(x)\n"
        "    return x / n\n"
    ), [TracerLeakRule()])
    assert res.findings == [], [f.render() for f in res.findings]


def test_tracer_static_args_exempt(tmp_path):
    """Parameters named in static_argnums are concrete at trace time —
    branching on them is the sanctioned pattern, not a finding."""
    res = lint_source(tmp_path, (
        "import jax\n"
        "from functools import partial\n"
        "@partial(jax.jit, static_argnums=(1,))\n"
        "def f(x, n):\n"
        "    if n > 2:\n"
        "        return x * n\n"
        "    return x\n"
    ), [TracerLeakRule()])
    assert res.findings == [], [f.render() for f in res.findings]


def test_tracer_numpy_on_traced(tmp_path):
    res = lint_source(tmp_path, (
        "import jax\n"
        "import numpy as np\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return np.sum(x)\n"
    ), [TracerLeakRule()])
    assert len(res.findings) == 1
    assert "numpy.sum" in res.findings[0].message


def test_tracer_jit_in_loop_warns(tmp_path):
    res = lint_source(tmp_path, (
        "import jax\n"
        "def build(fns, x):\n"
        "    outs = []\n"
        "    for fn in fns:\n"
        "        outs.append(jax.jit(fn)(x))\n"
        "    return outs\n"
    ), [TracerLeakRule()])
    assert len(res.findings) == 1
    f = res.findings[0]
    assert f.severity == "warning"
    assert "inside a loop body" in f.message


def test_tracer_unhashable_static_arg(tmp_path):
    res = lint_source(tmp_path, (
        "import jax\n"
        "def run(f, x):\n"
        "    step = jax.jit(f, static_argnums=(1,))\n"
        "    return step(x, [1, 2, 3])\n"
    ), [TracerLeakRule()])
    assert len(res.findings) == 1
    assert "unhashable" in res.findings[0].message


# ------------------------------------------------------ compile-site census


def test_census_counts_construction_sites(tmp_path):
    rule = CompileSiteCensusRule()
    res = lint_source(tmp_path, (
        "import jax\n"
        "from functools import partial\n"
        "@partial(jax.jit, donate_argnums=(0,))\n"
        "def step(s, x):\n"
        "    return s + x\n"
        "def build(fn, p, x):\n"
        "    lowered = jax.jit(fn).lower(p, x)\n"
        "    return lowered.compile()\n"
    ), [rule])
    kinds = sorted(s["kind"] for s in rule.sites)
    assert kinds == ["compile", "jit", "jit", "lower"], rule.sites
    donated = [s for s in rule.sites if s.get("donate_argnums")]
    assert donated and donated[0]["donate_argnums"] == [0]
    # every non-allowlisted site is a WARNING (prospective discipline,
    # never an immediate error)
    assert res.findings
    assert all(f.severity == "warning" for f in res.findings)


def test_census_ignores_re_compile_and_str_lower(tmp_path):
    """`.compile`/`.lower` only count when the receiver is jit-derived:
    re.compile() and str.lower() are not compile sites."""
    rule = CompileSiteCensusRule()
    lint_source(tmp_path, (
        "import re\n"
        "def f(s):\n"
        "    return re.compile(s.lower())\n"
    ), [rule])
    assert rule.sites == []


def test_committed_census_matches_fresh_scan():
    """The NEWEST committed docs/compile_sites_r*.json stays truthful:
    a fresh scan finds exactly the committed construction sites,
    compared on the line-independent keys
    (path::kind::enclosing#occurrence) so unrelated edits don't churn
    this test. If you add or remove a compile site, regenerate with
    `python tools/graftlint --census-json docs/compile_sites_rNN.json`
    (bump NN — earlier rounds stay committed as history)."""
    import glob

    rounds = sorted(glob.glob(
        os.path.join(REPO, "docs", "compile_sites_r*.json")))
    assert rounds, "no committed census round"
    committed = json.load(open(rounds[-1]))
    rule = CompileSiteCensusRule()
    engine.run(REPO, [rule])
    fresh = {site_key(s) for s in rule.sites}
    recorded = {site_key(s) for s in committed["sites"]}
    assert fresh == recorded, (
        f"census drift: new={sorted(fresh - recorded)} "
        f"gone={sorted(recorded - fresh)}")
    assert committed["n_sites"] == len(committed["sites"])
    # The serve engine's AOT path resolves through the module-local
    # helper summary — the sites the registry (ROADMAP item 5) most
    # needs are present by name.
    assert "cyclegan_tpu/serve/engine.py::compile::" \
           "InferenceEngine.__init__#1" in recorded
    assert "cyclegan_tpu/parallel/collective.py::shard_map::" \
           "shard_map_train_step#1" in recorded


# ------------------------------------------- suppressions and the baseline


def test_suppression_requires_reason(tmp_path):
    src_no_reason = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    if x > 0:  # graftlint: disable=tracer-leak\n"
        "        return x\n"
        "    return -x\n")
    res = lint_source(tmp_path, src_no_reason, [TracerLeakRule()])
    rules_hit = sorted(f.rule for f in res.findings)
    # the finding survives AND the reasonless disable is itself reported
    assert rules_hit == ["suppression", "tracer-leak"], rules_hit

    src_with_reason = src_no_reason.replace(
        "disable=tracer-leak",
        "disable=tracer-leak -- demo: concrete at trace time here")
    res = lint_source(tmp_path, src_with_reason, [TracerLeakRule()])
    assert res.findings == []
    assert len(res.suppressed) == 1
    assert res.ok


def test_baseline_grandfathers_one_to_one_and_reports_stale(tmp_path):
    src = ("import jax\n"
           "@jax.jit\n"
           "def f(x):\n"
           "    return float(x)\n")
    res = lint_source(tmp_path, src, [TracerLeakRule()])
    assert len(res.findings) == 1
    fp = res.findings[0].fingerprint
    baseline = [
        {"rule": "tracer-leak", "path": "mod.py", "fingerprint": fp,
         "reason": "grandfathered for the test"},
        {"rule": "tracer-leak", "path": "gone.py", "fingerprint": "x#1",
         "reason": "stale entry"},
    ]
    res = lint_source(tmp_path, src, [TracerLeakRule()], baseline=baseline)
    assert res.findings == [] and res.ok
    assert len(res.baselined) == 1
    assert len(res.stale_baseline) == 1  # informational, never failing


def test_baseline_fingerprints_survive_line_shifts(tmp_path):
    """Fingerprints exclude line numbers: prepending code to the file
    must not invalidate the baseline entry."""
    src = ("import jax\n"
           "@jax.jit\n"
           "def f(x):\n"
           "    return float(x)\n")
    fp = lint_source(tmp_path, src, [TracerLeakRule()]).findings[0].fingerprint
    shifted = "import os\n\nPAD = os.sep\n\n" + src
    fp2 = lint_source(tmp_path, shifted,
                      [TracerLeakRule()]).findings[0].fingerprint
    assert fp == fp2


# --------------------------------------------------- whole-repo self-gate


def test_repo_zero_unsuppressed_findings_under_committed_baseline():
    """THE acceptance gate: all four rules over the whole scan set,
    against the committed graftlint_baseline.json — zero live findings,
    zero stale entries. A new compile site (or any regression of the
    donation/no-sync/tracer discipline) fails here before it ever
    reaches chip time."""
    baseline = engine.load_baseline(
        os.path.join(REPO, engine.BASELINE_NAME))
    assert baseline, "committed graftlint_baseline.json missing or empty"
    res = engine.run(REPO, make_rules(), baseline=baseline)
    assert res.findings == [], "\n".join(f.render() for f in res.findings)
    assert res.ok
    assert res.stale_baseline == [], res.stale_baseline
    # the corpus lives under tests/ and must stay OUT of the scan set
    assert res.files_scanned > 50
    assert all(r in res.rules_run for r in ALL_RULES)


def test_cli_json_output_is_one_parseable_line(capsys):
    from graftlint import cli

    rc = cli.main(["--repo", REPO, "--json"])
    out = capsys.readouterr().out
    assert rc == 0
    lines = [ln for ln in out.splitlines() if ln.strip()]
    assert len(lines) == 1  # the repo tooling contract: ONE json line
    rec = json.loads(lines[0])
    assert rec["tool"] == "graftlint" and rec["ok"] is True
    assert rec["findings"] == []


def test_cli_exit_code_on_findings(capsys):
    from graftlint import cli

    rc = cli.main(["--repo", REPO, os.path.join(CORPUS),
                   "--no-baseline", "--rules", "donation-aliasing"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "graftlint FAILED" in out
    assert out.count("donation-aliasing") >= 2  # both bug fixtures


# ----------------------------------------------------- obs_report wiring


def test_obs_report_notes_lint_verdict(tmp_path):
    from obs_report import fold, load_lint_verdict, render

    jsonl = tmp_path / "telemetry.jsonl"
    jsonl.write_text('{"event": "epoch", "epoch": 0, "mfu": 0.1}\n')
    (tmp_path / "graftlint.json").write_text(json.dumps({
        "tool": "graftlint", "ok": True, "files_scanned": 9,
        "rules": ["donation-aliasing"], "counts": {},
        "n_suppressed": 1, "n_baselined": 2, "findings": [],
    }) + "\n")
    lint = load_lint_verdict(str(jsonl))
    assert lint is not None and lint["ok"]
    report = fold([{"event": "epoch", "epoch": 0}], 0)
    report["lint"] = lint
    text = render(report)
    assert "static discipline (graftlint preflight)" in text
    assert "verdict: PASSED" in text
    assert "1 suppressed, 2 baselined" in text


def test_obs_report_without_lint_file_unchanged(tmp_path):
    from obs_report import fold, load_lint_verdict, render

    jsonl = tmp_path / "telemetry.jsonl"
    jsonl.write_text('{"event": "epoch", "epoch": 0}\n')
    assert load_lint_verdict(str(jsonl)) is None
    text = render(fold([{"event": "epoch", "epoch": 0}], 0))
    assert "graftlint" not in text
