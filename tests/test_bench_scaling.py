"""Weak-scaling harness mechanics (bench_scaling.py): runs over the
8-device virtual mesh and emits one well-formed JSON line. Efficiency
values are meaningless on virtual CPU devices (they share host cores);
only the measurement machinery is under test."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_scaling_harness_emits_json():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    r = subprocess.run(
        [sys.executable, "bench_scaling.py", "--image", "32", "--batch", "2",
         "--tiny", "--scan_steps", "2", "--iters", "1"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=900,
    )
    assert r.returncode == 0, r.stderr
    lines = r.stdout.strip().splitlines()
    assert len(lines) == 1
    d = json.loads(lines[0])
    assert d["metric"] == "weak_scaling_efficiency"
    assert d["devices"] == 8
    assert set(d["images_per_sec"]) == {"1", "2", "4", "8"}
    assert all(v > 0 for v in d["images_per_sec"].values())
