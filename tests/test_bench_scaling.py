"""Weak-scaling harness mechanics (bench_scaling.py): runs over the
8-device virtual mesh and emits one well-formed JSON line. Efficiency
values are meaningless on virtual CPU devices (they share host cores);
only the measurement machinery is under test."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_parse_grid():
    sys.path.insert(0, REPO)
    from bench_scaling import _parse_grid

    assert _parse_grid("8x1,4x2,2x4") == [(8, 1), (4, 2), (2, 4)]
    assert _parse_grid("8") == [(8, 1)]  # bare dp: spatial defaults to 1


def test_hbm_ledger_divides_by_spatial():
    sys.path.insert(0, REPO)
    from bench_scaling import hbm_ledger

    # b1/1024^2 matches the b4/512^2 anchor's activation volume exactly.
    flat = hbm_ledger(1024, 1, 1, remat=True)
    assert flat["predicted_temp_gb"] == 10.75
    sharded = hbm_ledger(1024, 1, 4, remat=True)
    assert sharded["predicted_temp_gb"] == pytest.approx(10.75 / 4, abs=0.01)
    assert sharded["fits"]
    # Holding the 512^2 record's per-shard batch does NOT fit unsharded.
    assert not hbm_ledger(1024, 4, 1, remat=True)["fits"]


def test_grid_emit_efficiency_and_ledger(capsys):
    sys.path.insert(0, REPO)
    import argparse

    from bench_scaling import _emit

    args = argparse.Namespace(
        grid="8x1,4x2", batch=1, image=1024, spatial_impl="halo",
        remat=True, accum=2)
    # Equal-n cells: efficiency isolates the spatial-sharding overhead
    # (per-device ips of the LAST-measured max-n cell / first min-n).
    _emit({(8, 1): 80.0, (4, 2): 72.0}, 8, args)
    d = json.loads(capsys.readouterr().out.strip())
    assert d["mode"] == "grid"
    assert d["value"] == pytest.approx(0.9)
    assert d["images_per_sec"] == {"8x1": 80.0, "4x2": 72.0}
    # Ledger reflects the most-sharded measured cell (spatial=2 here).
    assert d["hbm_ledger"]["predicted_temp_gb"] == pytest.approx(
        10.75 / 2, abs=0.01)
    # Zero completed cells: the ledger falls back to the ATTEMPTED grid
    # instead of silently reporting the unsharded footprint.
    _emit({}, 8, args)
    d = json.loads(capsys.readouterr().out.strip())
    assert d["error"] == "no mesh size completed"
    assert d["hbm_ledger"]["predicted_temp_gb"] == pytest.approx(
        10.75 / 2, abs=0.01)


@pytest.mark.slow
def test_scaling_harness_emits_json():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    r = subprocess.run(
        [sys.executable, "bench_scaling.py", "--image", "32", "--batch", "2",
         "--tiny", "--scan_steps", "2", "--iters", "1"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=900,
    )
    assert r.returncode == 0, r.stderr
    lines = r.stdout.strip().splitlines()
    assert len(lines) == 1
    d = json.loads(lines[0])
    assert d["metric"] == "weak_scaling_efficiency"
    assert d["devices"] == 8
    assert set(d["images_per_sec"]) == {"1", "2", "4", "8"}
    assert all(v > 0 for v in d["images_per_sec"].values())
