"""Guard semantics of tools/chip_sweep.py (the on-chip sweep tool).

These pin the safety rails, not measurements: the spec grammar rejects
malformed/zero-valued specs before any compile, pallas specs off-CPU
are recorded as refusals without aborting the rest of the sweep
(remote-compiling the Mosaic program is tunnel-lethal —
docs/TUNNEL_POSTMORTEM.md incident 2), and a corrupt record file aborts
BEFORE any compile instead of being silently reset (each record cost
minutes of tunnel compile time). Grammar tests import the tool's own
parse_spec so regex drift cannot silently diverge from the tests.
All subprocess runs avoid initializing a TPU backend.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOL = os.path.join(REPO, "tools", "chip_sweep.py")

sys.path.insert(0, os.path.join(REPO, "tools"))
import chip_sweep  # noqa: E402  (parse_spec is importable without jax)


def _run(args, record_path, platforms="cpu", extra_env=None):
    env = dict(os.environ)
    env["CYCLEGAN_SWEEP_RECORD"] = str(record_path)
    if platforms is None:
        env.pop("JAX_PLATFORMS", None)
    else:
        env["JAX_PLATFORMS"] = platforms
    env.update(extra_env or {})
    return subprocess.run([sys.executable, TOOL, *args],
                          capture_output=True, text=True, env=env,
                          cwd=REPO, timeout=120)


def test_bad_spec_rejected(tmp_path):
    r = _run(["scan:i512b8"], tmp_path / "rec.json")  # parts out of order
    assert r.returncode != 0
    assert "bad spec" in (r.stdout + r.stderr)


def test_zero_k_rejected_not_coerced(tmp_path):
    # the regex's \d+ admits 0; `k or 8` would silently measure K=8 and
    # record it under the k0 key — must be rejected up front instead
    rec = tmp_path / "rec.json"
    r = _run(["scan:b16k0"], rec)
    assert r.returncode != 0
    assert "must be >= 1" in (r.stdout + r.stderr)
    assert not rec.exists()


def test_whole_spec_list_validated_before_any_run(tmp_path):
    # a bad spec LATER in the list aborts before the first (expensive)
    # spec starts measuring
    rec = tmp_path / "rec.json"
    r = _run(["scan:b2i64", "scan:b0"], rec)
    assert r.returncode != 0
    assert "must be >= 1" in (r.stdout + r.stderr)
    assert not rec.exists()  # nothing measured, nothing recorded


def test_no_args_prints_usage(tmp_path):
    r = _run([], tmp_path / "rec.json")
    assert r.returncode != 0
    assert "Spec grammar" in (r.stdout + r.stderr)


def test_pallas_off_cpu_records_refusal_and_continues(tmp_path):
    # refusal is a recorded RESULT (exit 0), not an abort: an unattended
    # multi-spec sweep must not lose its remaining specs. Use a bad
    # FOLLOWING spec? No — use only refusal specs so no compile runs.
    rec = tmp_path / "rec.json"
    r = _run(["scan:b16pallas", "scan:b8pallas"], rec, platforms=None)
    assert r.returncode == 0, (r.stdout, r.stderr)
    out = r.stdout + r.stderr
    assert "refusing to send" in out
    assert "CYCLEGAN_ALLOW_PALLAS_REMOTE" in out
    rows = json.loads(rec.read_text())
    assert [row["key"] for row in rows] == ["scan:b16pallas", "scan:b8pallas"]
    assert all(row["error"].startswith("refused:") for row in rows)


def test_epilogue_spec_off_cpu_records_refusal(tmp_path):
    # pad_impl="epilogue" runs a Mosaic program inside the train step —
    # same remote-compile hazard as pallas specs, same refusal rail.
    rec = tmp_path / "rec.json"
    r = _run(["scan:b16epi"], rec, platforms=None)
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "refusing to send" in (r.stdout + r.stderr)
    rows = json.loads(rec.read_text())
    assert rows[0]["key"] == "scan:b16epi"
    assert rows[0]["error"].startswith("refused:")


def test_pallas_allowed_on_cpu_platform(tmp_path):
    # JAX_PLATFORMS=cpu (re-asserted into jax.config) makes pallas specs
    # legal: they never touch the remote-compile leg. Parse-only check —
    # _pallas_blocked must return None — via a tiny in-process probe.
    code = (
        "import os; os.environ['JAX_PLATFORMS']='cpu';"
        "import sys; sys.path.insert(0, 'tools'); sys.path.insert(0, '.');"
        "from cyclegan_tpu.utils.platform import ensure_platform_from_env;"
        "ensure_platform_from_env();"
        "import chip_sweep; assert chip_sweep._pallas_blocked() is None;"
        "print('ok')"
    )
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, cwd=REPO, timeout=120)
    assert r.returncode == 0 and "ok" in r.stdout, (r.stdout, r.stderr)


def test_accum_spec_routes_to_bench_accum(tmp_path, monkeypatch):
    """run_spec('accum:...') must call bench.bench_accum with b as the
    MICRObatch and k as the accumulation count, and record img/s."""
    import types

    calls = {}
    stub = types.ModuleType("bench")

    def fake_accum(dtype, micro, image, accum, norm_impl, pad_mode,
                   pad_impl, grad_impl, trunk_impl, upsample_impl):
        calls.update(micro=micro, image=image, accum=accum,
                     pad_mode=pad_mode, grad_impl=grad_impl,
                     upsample_impl=upsample_impl)
        return 12.34

    stub.bench_accum = fake_accum
    monkeypatch.setitem(sys.modules, "bench", stub)
    monkeypatch.setattr(chip_sweep, "RECORD_PATH",
                        str(tmp_path / "rec.json"))
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    chip_sweep.run_spec("accum:b2k4zeroi512")
    assert calls == {"micro": 2, "image": 512, "accum": 4,
                     "pad_mode": "zero", "grad_impl": "combined",
                     "upsample_impl": "dense"}
    rows = json.loads((tmp_path / "rec.json").read_text())
    assert rows[0]["key"] == "accum:b2k4zeroi512"
    assert rows[0]["img_per_sec"] == 12.34


def test_classify_error_oom_vs_infra_vs_other():
    # The actual r5 failure string (docs/bench_sweeps.json) must classify
    # as infra, a plain OOM as a result, and anything else as other.
    assert chip_sweep.classify_error(
        "JaxRuntimeError: INTERNAL: http://127.0.0.1:8083/remote_compile: "
        "HTTP 500: tpu_compile_helper subprocess exit code 1") == "infra"
    assert chip_sweep.classify_error(
        "XlaRuntimeError: RESOURCE_EXHAUSTED: Attempting to allocate "
        "12.5G") == "oom"
    assert chip_sweep.classify_error("ValueError: bad shapes") == "other"
    # An OOM whose message also mentions the relay is still an OOM: it
    # IS the measurement the sweep exists to take.
    assert chip_sweep.classify_error(
        "remote_compile returned RESOURCE_EXHAUSTED: out of memory"
    ) == "oom"


def test_infra_error_not_recorded_and_flagged(tmp_path, monkeypatch):
    """A compile-relay death must not enter the ground-truth record file
    (it says nothing about the config), and run_spec must report it so
    main() can exit nonzero for the autorun driver."""
    import types

    stub = types.ModuleType("bench")

    def die(*a, **k):
        raise RuntimeError(
            "INTERNAL: http://127.0.0.1:8083/remote_compile: HTTP 500: "
            "tpu_compile_helper subprocess exit code 1")

    stub.bench_scan = die
    monkeypatch.setitem(sys.modules, "bench", stub)
    monkeypatch.setattr(chip_sweep, "RECORD_PATH", str(tmp_path / "rec.json"))
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    assert chip_sweep.run_spec("scan:b2i64") is True
    assert not (tmp_path / "rec.json").exists()


def test_oom_recorded_as_result_row(tmp_path, monkeypatch):
    import types

    stub = types.ModuleType("bench")

    def die(*a, **k):
        raise RuntimeError("RESOURCE_EXHAUSTED: out of memory allocating 8G")

    stub.bench_scan = die
    monkeypatch.setitem(sys.modules, "bench", stub)
    monkeypatch.setattr(chip_sweep, "RECORD_PATH", str(tmp_path / "rec.json"))
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    assert chip_sweep.run_spec("scan:b2i64") is False
    rows = json.loads((tmp_path / "rec.json").read_text())
    assert rows[0]["key"] == "scan:b2i64"
    assert "RESOURCE_EXHAUSTED" in rows[0]["error"]


def test_main_exits_3_when_any_spec_dies_on_infra(tmp_path, monkeypatch):
    monkeypatch.setattr(chip_sweep, "RECORD_PATH", str(tmp_path / "rec.json"))
    monkeypatch.setattr(chip_sweep, "run_spec", lambda spec: True)
    monkeypatch.setattr(sys, "argv", ["chip_sweep.py", "scan:b2i64"])
    with pytest.raises(SystemExit) as exc:
        chip_sweep.main()
    assert exc.value.code == 3


def test_corrupt_record_aborts_before_measuring(tmp_path):
    rec = tmp_path / "rec.json"
    rec.write_text("{corrupt")
    r = _run(["scan:b2i64"], rec)
    assert r.returncode != 0
    assert "refusing to overwrite" in (r.stdout + r.stderr)
    # the corrupt file is untouched, and the abort beat any compile
    assert rec.read_text() == "{corrupt"


@pytest.mark.parametrize("spec,expect", [
    ("scan:b8",
     ("scan", 8, 8, False, "reflect", "pad", "combined", "resnet", "dense",
      False, 256)),
    ("scan:b16k16",
     ("scan", 16, 16, False, "reflect", "pad", "combined", "resnet", "dense",
      False, 256)),
    ("dispatch:b16",
     ("dispatch", 16, 1, False, "reflect", "pad", "combined", "resnet", "dense",
      False, 256)),
    ("dispatch:b1k1i64",
     ("dispatch", 1, 1, False, "reflect", "pad", "combined", "resnet", "dense",
      False, 64)),
    ("scan:b16pallasi512",
     ("scan", 16, 8, True, "reflect", "pad", "combined", "resnet", "dense",
      False, 512)),
    ("scan:b16zero",
     ("scan", 16, 8, False, "zero", "pad", "combined", "resnet", "dense",
      False, 256)),
    ("dispatch:b16k8zeroi512",
     ("dispatch", 16, 8, False, "zero", "pad", "combined", "resnet", "dense",
      False, 512)),
    ("scan:b16fused",
     ("scan", 16, 8, False, "reflect", "fused", "combined", "resnet", "dense",
      False, 256)),
    ("dispatch:b16k8fusedi512",
     ("dispatch", 16, 8, False, "reflect", "fused", "combined", "resnet", "dense",
      False, 512)),
    # epi = pad_impl="epilogue" (Pallas trunk epilogue; local-compile only)
    ("scan:b16epi",
     ("scan", 16, 8, False, "reflect", "epilogue", "combined", "resnet", "dense",
      False, 256)),
    ("dispatch:b16k8epii512",
     ("dispatch", 16, 8, False, "reflect", "epilogue", "combined", "resnet", "dense",
      False, 512)),
    ("dispatch:b16k8pf",
     ("dispatch", 16, 8, False, "reflect", "pad", "combined", "resnet", "dense",
      True, 256)),
    ("dispatch:b16k8zeropfi512",
     ("dispatch", 16, 8, False, "zero", "pad", "combined", "resnet", "dense",
      True, 512)),
    # fp = grad_impl="fusedprop" (shared-forward gradient engine);
    # pb = trunk_impl="perturb" (cheap trunk tier) — composable with the
    # pad words and with each other.
    ("scan:b16fp",
     ("scan", 16, 8, False, "reflect", "pad", "fusedprop", "resnet", "dense",
      False, 256)),
    ("scan:b16pb",
     ("scan", 16, 8, False, "reflect", "pad", "combined", "perturb", "dense",
      False, 256)),
    ("scan:b16fppb",
     ("scan", 16, 8, False, "reflect", "pad", "fusedprop", "perturb", "dense",
      False, 256)),
    ("scan:b16fusedfp",
     ("scan", 16, 8, False, "reflect", "fused", "fusedprop", "resnet", "dense",
      False, 256)),
    ("dispatch:b16k8zerofppbpfi512",
     ("dispatch", 16, 8, False, "zero", "pad", "fusedprop", "perturb", "dense",
      True, 512)),
    ("accum:b1k8fpi512",
     ("accum", 1, 8, False, "reflect", "pad", "fusedprop", "resnet", "dense",
      False, 512)),
    # zs = upsample_impl="zeroskip" (GANAX output decomposition, pure
    # XLA); zsf = "zeroskip_fused" (Pallas phase-conv kernel —
    # local-compile only, like epi/pallas) — after fp/pb, before pf.
    ("scan:b16zs",
     ("scan", 16, 8, False, "reflect", "pad", "combined", "resnet",
      "zeroskip", False, 256)),
    ("scan:b16zsf",
     ("scan", 16, 8, False, "reflect", "pad", "combined", "resnet",
      "zeroskip_fused", False, 256)),
    ("scan:b16fpzs",
     ("scan", 16, 8, False, "reflect", "pad", "fusedprop", "resnet",
      "zeroskip", False, 256)),
    ("dispatch:b16k8zspfi512",
     ("dispatch", 16, 8, False, "reflect", "pad", "combined", "resnet",
      "zeroskip", True, 512)),
    # accum mode: b = MICRObatch, k = microbatches per update (default 8)
    ("accum:b1k8i512",
     ("accum", 1, 8, False, "reflect", "pad", "combined", "resnet", "dense",
      False, 512)),
    ("accum:b1i512",
     ("accum", 1, 8, False, "reflect", "pad", "combined", "resnet", "dense",
      False, 512)),
    ("accum:b2k4zeroi512",
     ("accum", 2, 4, False, "zero", "pad", "combined", "resnet", "dense",
      False, 512)),
])
def test_spec_grammar(spec, expect):
    assert chip_sweep.parse_spec(spec) == expect


@pytest.mark.parametrize("bad", ["scan:i512b8", "scan:b0", "scan:b16k0",
                                 "steps:b1", "scan:b8i0", "scan", "",
                                 "scan:b16zeropallas", "scan:b16zerofused",
                                 "scan:b16fusedzero", "scan:b16zeroepi",
                                 "scan:b16epifused", "scan:b16epipallas",
                                 "scan:b16pf",
                                 "dispatch:b16pfk8", "accum:b1pf",
                                 "accum:b0k8", "accum:b1k0",
                                 # order is fixed: fp before pb before
                                 # zs/zsf before pf
                                 "scan:b16pbfp", "dispatch:b16k8pffp",
                                 "scan:b16zsfp", "scan:b16pfzs",
                                 "scan:b16zszsf",
                                 "scan:b16fpfused",
                                 # pb has no epilogue trunk to fuse
                                 "scan:b16epipb"])
def test_spec_grammar_rejects(bad):
    with pytest.raises(SystemExit):
        chip_sweep.parse_spec(bad)
