"""Request-scoped distributed tracing (cyclegan_tpu/obs/trace.py):
head/tail sampling, span parenting across hedge twins, the fleet's
hop-tiling invariant (hop sum == e2e by construction), the zero-cost
pin (tracing adds no device dispatches), the X-Trace-Id HTTP echo,
Perfetto export schema on a pinned fixture, /metrics exposition, and
the obs_report unknown-kind census.

All fleet-level tests run against the FakeEngine control-plane double
(no compiles); the fixture stream in tests/data/trace_fixture.jsonl is
pinned so the Perfetto/critical-path assertions are deterministic.
"""

import io
import json
import os
import re
import sys
import threading
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))

from cyclegan_tpu.obs import (  # noqa: E402
    NULL_TRACE,
    NullTracer,
    Tracer,
)
from cyclegan_tpu.serve.fleet import (  # noqa: E402
    FleetConfig,
    FleetExecutor,
    ShedError,
)
from cyclegan_tpu.serve.fleet.admission import FleetRequest  # noqa: E402

from test_fleet import CLASSES, FakeEngine  # noqa: E402

FIXTURE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "data", "trace_fixture.jsonl")

HOP_NAMES = {"admit", "queue", "stack", "submit", "device", "resolve"}


class CapLogger:
    """MetricsLogger-shaped capture: the tracer only needs .event()."""

    def __init__(self):
        self.events = []
        self._lock = threading.Lock()

    def event(self, kind, /, **fields):
        with self._lock:
            self.events.append({"event": kind, **fields})

    def flush(self):
        pass

    def traces(self):
        with self._lock:
            return [e for e in self.events if e["event"] == "trace"]


def _img(size=32):
    return np.zeros((size, size, 3), np.float32)


def _fleet(engine, **kw):
    cfg = dict(n_replicas=1, capacity=64, max_batch=4, max_wait_ms=2.0)
    cfg.update(kw)
    return FleetExecutor(engine, FleetConfig(**cfg))


# -- sampling ---------------------------------------------------------------

def test_tracer_rejects_out_of_range_sample():
    for bad in (-0.1, 1.5):
        with pytest.raises(ValueError):
            Tracer(sample=bad)


def test_head_sampling_keeps_ok_traces_only_when_sampled():
    cap = CapLogger()
    t1 = Tracer(cap, sample=1.0)
    ctx = t1.trace("request")
    ctx.span_done("admit", None, ctx.root.t_start + 0.001)
    ctx.finish("ok")
    assert ctx.kept
    assert len(cap.traces()) == 1
    assert cap.traces()[0]["trace_id"] == ctx.trace_id
    assert re.fullmatch(r"[0-9a-f]{16}", ctx.trace_id)

    t0 = Tracer(cap, sample=0.0)
    ctx = t0.trace("request")
    ctx.finish("ok")
    assert not ctx.kept
    assert len(cap.traces()) == 1  # unchanged
    s = t0.stats()
    assert s["traces"] == 1 and s["emitted"] == 0


def test_failures_are_tail_sampled_at_sample_zero():
    cap = CapLogger()
    tr = Tracer(cap, sample=0.0)
    for status in ("shed", "expired", "deadline_miss", "error"):
        ctx = tr.trace("request")
        ctx.finish(status)
        assert ctx.kept, status
    kept = cap.traces()
    assert [e["status"] for e in kept] == ["shed", "expired",
                                           "deadline_miss", "error"]
    assert all(e["tail"] for e in kept)
    assert tr.stats()["tail"] == 4


def test_mark_tail_keeps_an_ok_trace_at_sample_zero():
    cap = CapLogger()
    tr = Tracer(cap, sample=0.0)
    ctx = tr.trace("request")
    ctx.mark_tail()  # hedge twin expired at pop while the primary won
    ctx.finish("ok")
    assert ctx.kept and cap.traces()[0]["status"] == "ok"


def test_first_finish_wins_and_late_spans_supplement():
    cap = CapLogger()
    tr = Tracer(cap, sample=1.0)
    ctx = tr.trace("request")
    assert ctx.finish("ok") is True
    assert ctx.finish("error") is False  # safety-net double finish
    assert cap.traces()[0]["status"] == "ok"
    # A span recorded after the flush (the cancelled hedge twin) lands
    # as a late=True supplement sharing the trace_id.
    t0 = ctx.root.t_start
    ctx.span_done("queued", t0, t0 + 0.005, outcome="won_elsewhere")
    late = [e for e in cap.traces() if e.get("late")]
    assert len(late) == 1
    assert late[0]["trace_id"] == ctx.trace_id
    assert late[0]["spans"][0]["name"] == "queued"
    assert tr.stats()["late"] == 1


def test_null_tracer_is_inert():
    nt = NullTracer()
    ctx = nt.trace("request")
    assert ctx is NULL_TRACE
    ctx.span_done("queue", 0.0, 1.0).end()
    ctx.event("shed")
    ctx.mark_tail()
    assert ctx.finish("error") is False
    assert nt.hop_histograms() == {}
    s = nt.stats()
    assert s.get("traces", 0) == 0 and s.get("emitted", 0) == 0


# -- hedge twins ------------------------------------------------------------

def test_hedge_twin_shares_the_trace_context():
    tr = Tracer(CapLogger(), sample=1.0)
    req = FleetRequest(_img(), 32, "base", CLASSES["interactive"])
    req.trace = tr.trace("request")
    twin = req.twin()
    assert twin.is_hedge and twin.trace is req.trace
    # Both copies' spans land on one trace_id: record from "each side".
    t0 = req.trace.root.t_start
    req.trace.span_done("device", t0, t0 + 0.001, replica=0, hedge=False)
    twin.trace.span_done("queued", t0, t0 + 0.002,
                         outcome="won_elsewhere", hedge=True)
    req.trace.finish("ok")
    spans = tr._logger.traces()[0]["spans"]
    assert {s["name"] for s in spans} == {"device", "queued"}
    # Parenting: every hop is a child of the root (id 0).
    assert all(s["parent"] == 0 for s in spans)


# -- fleet end-to-end -------------------------------------------------------

def test_fleet_spans_tile_the_request_interval():
    cap = CapLogger()
    tr = Tracer(cap, sample=1.0)
    eng = FakeEngine(buckets=(1, 4))
    fleet = _fleet(eng)
    try:
        futs = []
        for _ in range(8):
            ctx = tr.trace("request")
            futs.append(fleet.submit_raw(_img(), klass="batch",
                                         trace=ctx))
        for f in futs:
            f.result(timeout=30)
    finally:
        fleet.close()
    kept = [e for e in cap.traces() if not e.get("late")]
    assert len(kept) == 8
    for ev in kept:
        assert ev["status"] == "ok"
        assert (ev.get("attrs") or {}).get("class") == "batch"
        names = [s["name"] for s in ev["spans"]]
        assert set(names) == HOP_NAMES
        assert all(s["parent"] == 0 for s in ev["spans"])
        # The hops tile [t_start, t_end]: their sum reconciles with the
        # e2e duration by construction (<< the 5% acceptance bound;
        # tolerance only covers the 6-dp rounding in to_dict).
        hop_sum = sum(s["t1"] - s["t0"] for s in ev["spans"])
        assert ev["dur_s"] > 0
        assert abs(hop_sum - ev["dur_s"]) <= 1e-5 + 0.001 * ev["dur_s"]
    # Hop histograms feed /metrics: every hop folded, counts match.
    hists = tr.hop_histograms()
    assert set(hists) >= HOP_NAMES | {"request"}
    assert hists["device"]["count"] == 8


def test_tracing_adds_zero_device_dispatches():
    """The overhead pin: the same submission pattern traced at sample
    1.0 and untraced must produce IDENTICAL flush counts — tracing is
    pure host arithmetic on timestamps the pipeline already takes."""
    flushes = {}
    for label, tracer in (("untraced", None),
                          ("traced", Tracer(CapLogger(), sample=1.0))):
        eng = FakeEngine(buckets=(1, 4))
        fleet = _fleet(eng)
        try:
            for _ in range(2):  # two full batches, gapped deterministically
                futs = []
                for _ in range(4):
                    kw = {}
                    if tracer is not None:
                        kw["trace"] = tracer.trace("request")
                    futs.append(fleet.submit_raw(_img(), klass="batch",
                                                 **kw))
                for f in futs:
                    f.result(timeout=30)
        finally:
            fleet.close()
        flushes[label] = len(eng.flushes)
    assert flushes["traced"] == flushes["untraced"]


def test_shed_trace_is_kept_at_sample_zero_through_the_fleet():
    cap = CapLogger()
    tr = Tracer(cap, sample=0.0)
    eng = FakeEngine(buckets=(1,))
    eng.gate = threading.Event()
    fleet = _fleet(eng, capacity=1, max_batch=1, max_wait_ms=0.0)
    try:
        pinned = fleet.submit_raw(_img(), klass="best_effort")
        assert eng.entered.wait(timeout=10)
        queued = fleet.submit_raw(_img(), klass="best_effort")
        ctx = tr.trace("request")
        with pytest.raises(ShedError):
            fleet.submit_raw(_img(), klass="best_effort", trace=ctx)
        eng.gate.set()
        pinned.result(timeout=30)
        queued.result(timeout=30)
    finally:
        fleet.close()
    kept = cap.traces()
    assert len(kept) == 1
    ev = kept[0]
    assert ev["status"] == "shed" and ev["tail"] and not ev["sampled"]
    sheds = [e for e in ev.get("events") or [] if e["name"] == "shed"]
    assert sheds and sheds[0]["reason"] == "rejected"


# -- HTTP: X-Trace-Id + /metrics -------------------------------------------

def test_http_x_trace_id_and_metrics_exposition():
    import urllib.request

    from cyclegan_tpu.serve.server import make_server

    cap = CapLogger()
    tr = Tracer(cap, sample=1.0)
    eng = FakeEngine(buckets=(1, 4))
    fleet = _fleet(eng)
    server, app = make_server(fleet, port=0, fleet=True, tracer=tr)
    host, port = server.server_address[:2]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        buf = io.BytesIO()
        np.save(buf, np.zeros((32, 32, 3), np.uint8))
        req = urllib.request.Request(
            f"http://{host}:{port}/translate?class=interactive",
            data=buf.getvalue(), method="POST")
        with urllib.request.urlopen(req, timeout=30) as r:
            assert r.status == 200
            trace_id = r.headers["X-Trace-Id"]
        assert re.fullmatch(r"[0-9a-f]{16}", trace_id)
        # The echoed id resolves to an emitted span graph.
        by_id = {e["trace_id"]: e for e in cap.traces()}
        assert trace_id in by_id
        assert {s["name"] for s in by_id[trace_id]["spans"]} == HOP_NAMES

        with urllib.request.urlopen(
                f"http://{host}:{port}/metrics", timeout=30) as r:
            assert r.status == 200
            assert r.headers["Content-Type"].startswith("text/plain")
            text = r.read().decode()
    finally:
        server.shutdown()
        fleet.close()

    # Prometheus text exposition 0.0.4: every sample line parses, HELP/
    # TYPE comments name real families, histogram buckets are cumulative.
    sample_re = re.compile(
        r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? "
        r"(?:[-+]?(?:\d+\.?\d*(?:[eE][-+]?\d+)?|nan|inf))$")
    families = set()
    for line in text.rstrip("\n").split("\n"):
        if line.startswith("# TYPE "):
            name, kind = line.split()[2:4]
            assert name not in families, f"duplicate TYPE for {name}"
            families.add(name)
            assert kind in ("counter", "gauge", "summary", "histogram")
            continue
        if line.startswith("#"):
            continue
        assert sample_re.match(line), f"unparseable sample line: {line!r}"
    assert "cyclegan_serve_requests_total" in families
    assert "cyclegan_trace_sample" in families
    assert "cyclegan_trace_hop_seconds" in families
    # Cumulative buckets: the device hop's +Inf count equals _count.
    bucket_lines = [ln for ln in text.split("\n")
                    if ln.startswith("cyclegan_trace_hop_seconds_bucket")
                    and 'hop="device"' in ln]
    assert bucket_lines, "no device-hop histogram buckets"
    counts = [float(ln.rsplit(" ", 1)[1]) for ln in bucket_lines]
    assert counts == sorted(counts), "histogram buckets not cumulative"
    inf_line = [ln for ln in bucket_lines if 'le="+Inf"' in ln]
    count_line = [ln for ln in text.split("\n")
                  if ln.startswith("cyclegan_trace_hop_seconds_count")
                  and 'hop="device"' in ln]
    assert inf_line and count_line
    assert (inf_line[0].rsplit(" ", 1)[1]
            == count_line[0].rsplit(" ", 1)[1])


# -- Perfetto export on the pinned fixture ---------------------------------

def test_trace_timeline_folds_fixture_with_late_supplement():
    import trace_timeline

    traces = trace_timeline.load_traces(FIXTURE)
    assert len(traces) == 3  # the late event merged, not a 4th trace
    by_id = {t["trace_id"]: t for t in traces}
    hedged = by_id["bbbb0000111122223333444455556666"]
    assert any(s["name"] == "queued" for s in hedged["spans"])
    assert len(hedged["spans"]) == 7


def test_trace_timeline_perfetto_schema_on_fixture(tmp_path):
    import trace_timeline

    out = tmp_path / "trace.perfetto.json"
    rc = trace_timeline.main([FIXTURE, "--out", str(out), "--json"])
    assert rc == 0
    doc = json.loads(out.read_text())
    assert isinstance(doc["traceEvents"], list) and doc["traceEvents"]
    assert doc["displayTimeUnit"] == "ms"
    names_by_tid = {}
    for ev in doc["traceEvents"]:
        assert ev["ph"] in ("X", "M", "i")
        assert ev["pid"] == 1 and isinstance(ev["tid"], int)
        if ev["ph"] == "X":
            assert ev["dur"] >= 0 and ev["ts"] >= 0
            assert "name" in ev
        elif ev["ph"] == "M" and ev["name"] == "thread_name":
            names_by_tid[ev["tid"]] = ev["args"]["name"]
        elif ev["ph"] == "i":
            assert ev["s"] == "t"
    tracks = set(names_by_tid.values())
    assert {"requests", "queue", "hedge lane",
            "replica 0", "replica 1"} <= tracks
    # Hop slices land on their replica's track; hedged work (the
    # winning twin's device hop, the cancelled twin's queue residency)
    # lands on the hedge lane.
    tid_of = {v: k for k, v in names_by_tid.items()}
    hops = [ev for ev in doc["traceEvents"] if ev.get("cat") == "hop"]
    assert any(ev["tid"] == tid_of["replica 1"] and ev["name"] == "queue"
               for ev in hops)
    assert any(ev["tid"] == tid_of["replica 0"] and ev["name"] == "device"
               for ev in hops)
    for name in ("device", "queued"):  # the hedged pair from trace bbbb
        assert any(ev["tid"] == tid_of["hedge lane"]
                   and ev["name"] == name for ev in hops)


def test_trace_timeline_critical_path_reconciles_on_fixture():
    import trace_timeline

    table = trace_timeline.critical_path(trace_timeline.load_traces(FIXTURE))
    assert set(table) == {"class=interactive tenant=-",
                          "class=batch tenant=-",
                          "class=best_effort tenant=-"}
    for label in ("class=interactive tenant=-", "class=batch tenant=-"):
        g = table[label]
        # The acceptance bound: per-request hop sum within 5% of e2e.
        assert g["recon_frac"] is not None and g["recon_frac"] <= 0.05
        assert set(g["hops"]) >= {"admit", "queue", "device"}
    rendered = trace_timeline.render_table(table)
    assert "reconciliation" in rendered


def test_trace_timeline_empty_stream_exits_nonzero(tmp_path, capsys):
    import trace_timeline

    p = tmp_path / "empty.jsonl"
    p.write_text('{"event": "manifest", "t": 0.0}\n')
    assert trace_timeline.main([str(p)]) == 1


# -- obs_report: trace section + unknown-kind census ------------------------

def test_obs_report_names_unknown_kinds_and_folds_traces(tmp_path):
    import obs_report

    events, skipped = obs_report.load_events(FIXTURE)
    lines = events + [{"event": "from_the_future", "t": 9.9},
                      {"event": "from_the_future", "t": 9.95}]
    report = obs_report.fold(lines, skipped)
    # The satellite contract: an unrecognized kind is counted and NAMED
    # in the render, never silently dropped.
    assert report["unknown_kinds"] == {"from_the_future": 2}
    text = obs_report.render(report)
    assert "unknown event kinds" in text
    assert "from_the_future x2" in text
    roll = report["trace_rollup"]
    assert roll["n_traces"] == 3 and roll["n_late_supplements"] == 1
    assert roll["statuses"] == {"ok": 2, "shed": 1}
    assert roll["n_tail_kept"] == 1
    assert roll["slowest"][0]["dur_ms"] == 18.0
    assert "-- request traces (3 kept" in text
    assert "bbbb0000111122223333444455556666" in text


def test_obs_report_serving_stream_without_traces_renders_absent():
    import obs_report

    stream = [{"event": "fleet_flush", "t": 0.1, "n": 2, "trigger": "full",
               "replica": 0}]
    text = obs_report.render(obs_report.fold(stream))
    assert "request traces: absent" in text


# -- static discipline ------------------------------------------------------

def test_no_sync_scan_covers_trace_module():
    from check_no_sync import hot_path_entries, run_check

    entries = dict(hot_path_entries())
    # obs/ expands into the hot path with zero sanctioned fetches: the
    # tracer must stay pure host arithmetic.
    assert entries.get("cyclegan_tpu/obs/trace.py") is False
    assert run_check() == []
