"""Trunk-layout checkpoint conversion (utils/convert.py).

The strong property: training N steps unrolled, converting the FULL state
(params + Adam moments) to the scanned layout, and continuing must produce
the same losses as never converting — the conversion is a pure relabeling
of the optimization trajectory.
"""

import os
import pathlib
import subprocess
import sys

import jax
import numpy as np

REPO_ROOT = str(pathlib.Path(__file__).resolve().parents[1])

from cyclegan_tpu.train import create_state, make_train_step
from cyclegan_tpu.utils.convert import convert_state_trunk


def _batch(config, seed):
    rng = np.random.RandomState(seed)
    s = config.model.image_size
    n = 2
    x = rng.rand(n, s, s, 3).astype(np.float32) * 2 - 1
    y = rng.rand(n, s, s, 3).astype(np.float32) * 2 - 1
    return x, y, np.ones((n,), np.float32)


def test_conversion_preserves_training_trajectory(tiny_config):
    import dataclasses

    cfg_unrolled = tiny_config
    cfg_scanned = dataclasses.replace(
        tiny_config, model=dataclasses.replace(tiny_config.model, scan_blocks=True)
    )
    n_blocks = cfg_unrolled.model.generator.num_residual_blocks

    step_u = jax.jit(make_train_step(cfg_unrolled, 2))
    step_s = jax.jit(make_train_step(cfg_scanned, 2))

    state = create_state(cfg_unrolled, jax.random.PRNGKey(0))
    for i in range(2):  # builds non-trivial Adam moments
        state, _ = step_u(state, *_batch(cfg_unrolled, i))

    # Branch A: continue unrolled. Branch B: convert, continue scanned.
    state_a, metrics_a = step_u(state, *_batch(cfg_unrolled, 9))
    state_b = convert_state_trunk(state, n_blocks, "scanned")
    state_b, metrics_b = step_s(state_b, *_batch(cfg_scanned, 9))

    for k in metrics_a:
        np.testing.assert_allclose(
            float(metrics_a[k]), float(metrics_b[k]), rtol=1e-5, atol=1e-6, err_msg=k
        )

    # And the resulting params agree after mapping back.
    back = convert_state_trunk(state_b, n_blocks, "unrolled")
    for a, b in zip(jax.tree.leaves(state_a.g_params), jax.tree.leaves(back.g_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_convert_legacy_checkpoint_flags(tiny_config, tmp_path):
    """A pre-meta (legacy) slot holding a NON-default architecture:
    convert without flags must exit with the legacy-flag hint (not a raw
    orbax structure error), and must succeed when the training flags are
    repeated — the same contract translate.py/evaluate.py honor
    (round-2 ADVICE, convert.py)."""
    import argparse
    import json

    import pytest

    from cyclegan_tpu.utils import convert as convert_mod
    from cyclegan_tpu.utils.checkpoint import Checkpointer

    out = str(tmp_path / "legacy")
    state = create_state(tiny_config, jax.random.PRNGKey(0))
    ckpt = Checkpointer(out)
    ckpt.save(state, 3)  # meta=None: epoch-only sidecar, as pre-meta slots
    ckpt.close()

    def ns(**kw):
        base = dict(output_dir=out, to="scanned", image_size=32,
                    filters=None, residual_blocks=None)
        base.update(kw)
        return argparse.Namespace(**base)

    with pytest.raises(SystemExit, match="legacy checkpoint"):
        convert_mod.main(ns())

    convert_mod.main(ns(filters=4, residual_blocks=1))
    with open(os.path.join(out, "checkpoints", "meta.json")) as f:
        meta = json.load(f)
    assert meta["model"]["scan_blocks"] is True
    assert meta["model"]["generator"]["filters"] == 4
    assert meta["epoch"] == 3


def test_convert_cli_roundtrip(tmp_path):
    """Train 1 tiny epoch unrolled, convert the on-disk checkpoint to
    scanned, resume with --scan_blocks: the run must pick up cleanly."""
    out = str(tmp_path / "run")
    base = [
        sys.executable, "main.py", "--output_dir", out, "--batch_size", "2",
        "--verbose", "0", "--data_source", "synthetic", "--image_size", "32",
        "--synthetic_train_size", "4", "--synthetic_test_size", "2",
        # Tiny architecture: the roundtrip exercises layout conversion
        # and resume plumbing, which are width-independent — full-size
        # compiles dominated the whole tier-1 budget on small hosts.
        "--filters", "4", "--residual_blocks", "1",
    ]
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    r = subprocess.run(base + ["--epochs", "1"], capture_output=True, text=True,
                       env=env, cwd=REPO_ROOT, timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]

    r = subprocess.run(
        [sys.executable, "-m", "cyclegan_tpu.utils.convert", "--output_dir", out,
         "--to", "scanned", "--image_size", "32"],
        capture_output=True, text=True, env=env, cwd=REPO_ROOT, timeout=600,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "converted" in r.stdout

    # The sidecar now records the TARGET layout (self-describing slots).
    import json

    with open(os.path.join(out, "checkpoints", "meta.json")) as f:
        meta = json.load(f)
    assert meta["model"]["scan_blocks"] is True

    # Converting to the layout the sidecar already records refuses cleanly.
    r = subprocess.run(
        [sys.executable, "-m", "cyclegan_tpu.utils.convert", "--output_dir", out,
         "--to", "scanned"],
        capture_output=True, text=True, env=env, cwd=REPO_ROOT, timeout=600,
    )
    assert r.returncode != 0
    assert "already records" in (r.stdout + r.stderr)

    r = subprocess.run(base + ["--epochs", "2", "--scan_blocks"],
                       capture_output=True, text=True, env=env, cwd=REPO_ROOT,
                       timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "Resumed" in r.stdout
