"""Loss tests vs hand-computed scalars, including the lambda=10/lambda=5
weights (main.py:116-118) and sum/global_batch scaling (main.py:172-174)."""

import jax.numpy as jnp
import numpy as np

from cyclegan_tpu import losses


def w(n):
    return jnp.ones((n,), jnp.float32)


def test_mae_per_sample():
    a = jnp.asarray([[[1.0, 2.0]], [[0.0, 0.0]]])  # [2,1,2]
    b = jnp.asarray([[[0.0, 0.0]], [[1.0, 3.0]]])
    np.testing.assert_allclose(np.asarray(losses.mae(a, b)), [1.5, 2.0])


def test_mse_per_sample():
    a = jnp.asarray([[[1.0, 2.0]], [[0.0, 0.0]]])
    b = jnp.asarray([[[0.0, 0.0]], [[1.0, 3.0]]])
    np.testing.assert_allclose(np.asarray(losses.mse(a, b)), [2.5, 5.0])


def test_bce_matches_manual():
    y_true = jnp.asarray([[1.0], [0.0]])
    y_pred = jnp.asarray([[0.8], [0.3]])
    got = np.asarray(losses.bce(y_true, y_pred))
    want = [-np.log(0.8), -np.log(0.7)]
    np.testing.assert_allclose(got, want, rtol=1e-3)


def test_scaled_mean_divides_by_global_batch():
    # Two local samples but global batch 8 (DP with 4 replicas):
    per_sample = jnp.asarray([3.0, 5.0])
    got = losses.scaled_mean(per_sample, w(2), 8)
    assert float(got) == 1.0  # (3+5)/8


def test_weights_mask_padded_samples():
    per_sample = jnp.asarray([3.0, 5.0, 100.0])
    weights = jnp.asarray([1.0, 1.0, 0.0])  # third sample is padding
    got = losses.scaled_mean(per_sample, weights, 2)
    assert float(got) == 4.0


def test_generator_loss_lsgan():
    # D(fake) = 0.5 everywhere -> MSE(1, 0.5) = 0.25 per sample
    d_fake = jnp.full((2, 4, 4, 1), 0.5)
    got = losses.generator_loss(d_fake, w(2), 2)
    np.testing.assert_allclose(float(got), 0.25, rtol=1e-6)


def test_cycle_loss_lambda_10():
    real = jnp.zeros((1, 2, 2, 1))
    cycled = jnp.full((1, 2, 2, 1), 0.3)
    got = losses.cycle_loss(real, cycled, w(1), 1, lambda_cycle=10.0)
    np.testing.assert_allclose(float(got), 3.0, rtol=1e-6)


def test_identity_loss_lambda_5():
    real = jnp.zeros((1, 2, 2, 1))
    same = jnp.full((1, 2, 2, 1), 0.2)
    got = losses.identity_loss(real, same, w(1), 1, lambda_identity=5.0)
    np.testing.assert_allclose(float(got), 1.0, rtol=1e-6)


def test_discriminator_loss_half_sum():
    d_real = jnp.full((1, 2, 2, 1), 0.8)  # MSE(1, .8) = .04
    d_fake = jnp.full((1, 2, 2, 1), 0.4)  # MSE(0, .4) = .16
    got = losses.discriminator_loss(d_real, d_fake, w(1), 1)
    np.testing.assert_allclose(float(got), 0.5 * (0.04 + 0.16), rtol=1e-6)
