"""Distributed-without-a-cluster tests (SURVEY.md §4): on 8 virtual CPU
devices, the DP-sharded step must equal the single-device step, for both
the compiler-scheduled jit path and the explicit shard_map+psum path."""

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec
import numpy as np
import pytest

from cyclegan_tpu.parallel import (
    make_mesh_plan,
    pad_to_global_batch,
    shard_batch,
    shard_test_step,
    shard_train_step,
)
from cyclegan_tpu.parallel.collective import shard_map_train_step
from cyclegan_tpu.config import ParallelConfig
from cyclegan_tpu.train import create_state, make_test_step, make_train_step


@pytest.fixture(scope="module")
def batch(tiny_config):
    cfg = tiny_config
    n = 8
    kx, ky = jax.random.split(jax.random.PRNGKey(7))
    s = cfg.model.image_size
    x = np.asarray(jax.random.uniform(kx, (n, s, s, 3), minval=-1, maxval=1))
    y = np.asarray(jax.random.uniform(ky, (n, s, s, 3), minval=-1, maxval=1))
    w = np.ones((n,), np.float32)
    return x, y, w


@pytest.fixture()  # function-scoped: shard_train_step donates the state
def state0(tiny_config):
    return create_state(tiny_config, jax.random.PRNGKey(0))


def tree_allclose(a, b, rtol=2e-4, atol=1e-6, msg=""):
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_allclose(
            np.asarray(la, np.float32), np.asarray(lb, np.float32),
            rtol=rtol, atol=atol, err_msg=msg,
        )


def test_dp_jit_equals_single_device(tiny_config, state0, batch, devices):
    cfg, (x, y, w) = tiny_config, batch
    gbs = x.shape[0]

    # Single device (first CPU device only).
    single = jax.jit(make_train_step(cfg, gbs))
    s1, m1 = single(state0, jnp.asarray(x), jnp.asarray(y), jnp.asarray(w))

    # 8-way data parallel via compiler-scheduled sharding.
    plan = make_mesh_plan(ParallelConfig(), devices)
    assert plan.n_data == 8
    step = shard_train_step(plan, make_train_step(cfg, gbs))
    xs, ys, ws = shard_batch(plan, x, y, w)
    state_rep = jax.device_put(state0, NamedSharding(plan.mesh, PartitionSpec()))
    s8, m8 = step(state_rep, xs, ys, ws)

    for k in m1:
        np.testing.assert_allclose(float(m1[k]), float(m8[k]), rtol=2e-4, atol=1e-6, err_msg=k)
    tree_allclose(s1.g_params, s8.g_params, msg="g_params diverged")
    tree_allclose(s1.dx_params, s8.dx_params, msg="dx_params diverged")


def test_dp_shard_map_psum_equals_single_device(tiny_config, state0, batch, devices):
    cfg, (x, y, w) = tiny_config, batch
    gbs = x.shape[0]
    single = jax.jit(make_train_step(cfg, gbs))
    s1, m1 = single(state0, jnp.asarray(x), jnp.asarray(y), jnp.asarray(w))

    plan = make_mesh_plan(ParallelConfig(), devices)
    step = shard_map_train_step(plan, cfg, gbs)
    xs, ys, ws = shard_batch(plan, x, y, w)
    s8, m8 = step(state0, xs, ys, ws)

    for k in m1:
        np.testing.assert_allclose(float(m1[k]), float(m8[k]), rtol=2e-4, atol=1e-6, err_msg=k)
    tree_allclose(s1.g_params, s8.g_params, msg="g_params diverged (psum path)")
    tree_allclose(s1.f_params, s8.f_params, msg="f_params diverged (psum path)")


def test_dp_test_step_matches(tiny_config, state0, batch, devices):
    cfg, (x, y, w) = tiny_config, batch
    gbs = x.shape[0]
    m1 = jax.jit(make_test_step(cfg, gbs))(
        state0, jnp.asarray(x), jnp.asarray(y), jnp.asarray(w)
    )
    plan = make_mesh_plan(ParallelConfig(), devices)
    step = shard_test_step(plan, make_test_step(cfg, gbs))
    xs, ys, ws = shard_batch(plan, x, y, w)
    m8 = step(jax.device_put(state0, NamedSharding(plan.mesh, PartitionSpec())), xs, ys, ws)
    for k in m1:
        np.testing.assert_allclose(float(m1[k]), float(m8[k]), rtol=2e-4, atol=1e-6, err_msg=k)


def test_ragged_final_batch_padding(tiny_config, state0, devices):
    """5 real samples padded to a global batch of 8 across 8 devices must
    equal the unpadded 5-sample computation at the same global_batch_size
    (reference remainder semantics, main.py:32-33)."""
    cfg = tiny_config
    s = cfg.model.image_size
    kx, ky = jax.random.split(jax.random.PRNGKey(3))
    x5 = np.asarray(jax.random.uniform(kx, (5, s, s, 3), minval=-1, maxval=1))
    y5 = np.asarray(jax.random.uniform(ky, (5, s, s, 3), minval=-1, maxval=1))
    gbs = 8  # ceil-semantics: final batch of 5 at global batch 8

    m_ref = jax.jit(make_test_step(cfg, gbs))(
        state0, jnp.asarray(x5), jnp.asarray(y5), jnp.ones((5,), jnp.float32)
    )

    xp, yp, wp = pad_to_global_batch(x5, y5, gbs)
    assert xp.shape[0] == 8 and wp.sum() == 5
    plan = make_mesh_plan(ParallelConfig(), devices)
    step = shard_test_step(plan, make_test_step(cfg, gbs))
    xs, ys, ws = shard_batch(plan, xp, yp, wp)
    m_pad = step(jax.device_put(state0, NamedSharding(plan.mesh, PartitionSpec())), xs, ys, ws)
    for k in m_ref:
        np.testing.assert_allclose(float(m_ref[k]), float(m_pad[k]), rtol=2e-4, atol=1e-6, err_msg=k)


def test_spatial_sharding_compiles_and_matches(tiny_config, state0, batch, devices):
    """2-D mesh (4 data x 2 spatial): H-axis sharding — XLA inserts halo
    exchanges for the convs; results must match single-device."""
    cfg, (x, y, w) = tiny_config, batch
    gbs = x.shape[0]
    m1 = jax.jit(make_test_step(cfg, gbs))(
        state0, jnp.asarray(x), jnp.asarray(y), jnp.asarray(w)
    )
    plan = make_mesh_plan(ParallelConfig(spatial_parallelism=2), devices)
    assert plan.n_data == 4 and plan.n_spatial == 2
    step = shard_test_step(plan, make_test_step(cfg, gbs))
    xs, ys, ws = shard_batch(plan, x, y, w)
    m8 = step(jax.device_put(state0, NamedSharding(plan.mesh, PartitionSpec())), xs, ys, ws)
    for k in m1:
        np.testing.assert_allclose(float(m1[k]), float(m8[k]), rtol=5e-4, atol=1e-5, err_msg=k)
