"""InceptionV3 pool3 port (eval/inception.py).

No pretrained weights exist in this image, so these tests pin the
architecture (feature dim, stage geometry, parameter budget) and the npz
weight-loading contract — the parts a later weights drop depends on.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cyclegan_tpu.eval.inception import (
    InceptionV3Pool3,
    flatten_params,
    load_params_npz,
)


def _tiny_batch(n=1, s=299):
    return jnp.asarray(np.random.RandomState(0).rand(n, s, s, 3) * 2 - 1, jnp.float32)


def test_pool3_shape_and_param_budget():
    net = InceptionV3Pool3()
    x = _tiny_batch()
    variables = net.init(jax.random.PRNGKey(0), x)
    out = net.apply(variables, x)
    assert out.shape == (1, 2048)
    n_params = sum(
        a.size for a in jax.tree.leaves(variables["params"])
    )
    # InceptionV3 trunk (no logits/aux head) is ~21.8M params; BN moving
    # stats live in batch_stats, not params.
    assert 21_000_000 < n_params < 23_000_000, n_params
    assert "batch_stats" in variables


def test_npz_roundtrip_through_inception_features(tmp_path):
    """flatten_params -> npz -> InceptionFeatures reproduces the direct
    apply (including the 299 resize being a no-op at 299 input)."""
    from cyclegan_tpu.eval.features import InceptionFeatures

    net = InceptionV3Pool3()
    x = _tiny_batch()
    variables = net.init(jax.random.PRNGKey(1), x)
    path = str(tmp_path / "w.npz")
    np.savez(path, **flatten_params(variables))

    fx = InceptionFeatures(path)
    assert fx.dim == 2048
    np.testing.assert_allclose(
        np.asarray(fx(x)),
        np.asarray(net.apply(variables, x)),
        rtol=1e-5,
        atol=1e-5,
    )


def test_npz_validation_errors(tmp_path):
    net = InceptionV3Pool3()
    variables = jax.eval_shape(
        lambda: net.init(jax.random.PRNGKey(0), jnp.zeros((1, 299, 299, 3)))
    )
    flat = {
        k: np.zeros(v.shape, v.dtype)
        for k, v in flatten_params(
            jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), variables)
        ).items()
    }

    missing = dict(flat)
    missing.pop(sorted(missing)[0])
    p1 = str(tmp_path / "missing.npz")
    np.savez(p1, **missing)
    with pytest.raises(ValueError, match="missing"):
        load_params_npz(p1, variables)

    key = sorted(flat)[0]
    bad = dict(flat)
    bad[key] = np.zeros((1, 2, 3), np.float32)
    p2 = str(tmp_path / "bad.npz")
    np.savez(p2, **bad)
    with pytest.raises(ValueError, match="shape"):
        load_params_npz(p2, variables)


def test_random_inception_is_offline_default_and_deterministic():
    """`auto` with no weights file resolves to the random-weight
    InceptionV3 proxy (round-3 upgrade from the shallow random conv),
    whose embedding must be identical across instances (processes/hosts
    build their own params from the path-CRC seeds) and non-degenerate
    through all 48 layers."""
    from cyclegan_tpu.eval.features import (
        RandomInceptionFeatures,
        build_feature_extractor,
    )

    fx = build_feature_extractor("auto", None)
    assert fx.name == "random_inception_v3_pool3"
    rng = np.random.RandomState(0)
    imgs = (rng.rand(2, 64, 64, 3).astype(np.float32) * 2) - 1
    f1 = np.asarray(fx(imgs))
    assert f1.shape == (2, 2048)
    assert np.isfinite(f1).all()
    assert f1.std() > 1e-4  # not collapsed by the deep ReLU stack
    assert np.abs(f1[0] - f1[1]).max() > 1e-4  # distinguishes inputs
    f2 = np.asarray(RandomInceptionFeatures()(imgs))
    np.testing.assert_array_equal(f1, f2)


def test_auto_falls_back_on_unusable_weights(tmp_path):
    """build_feature_extractor('auto', bad_path) must warn and fall back
    to random features, never crash the training run."""
    from cyclegan_tpu.eval.features import build_feature_extractor

    p = str(tmp_path / "garbage.npz")
    np.savez(p, foo=np.zeros(3))
    fx = build_feature_extractor("auto", p)
    assert fx.name == "random_inception_v3_pool3"

    # A truncated/corrupt zip (np.load raises BadZipFile, not ValueError)
    # must also fall back, not abort training at startup.
    p2 = str(tmp_path / "truncated.npz")
    with open(p2, "wb") as f:
        f.write(b"PK\x03\x04corrupt")
    fx = build_feature_extractor("auto", p2)
    assert fx.name == "random_inception_v3_pool3"
