"""Pin the committed TPU-compiler report to the analytic models.

docs/aot_analysis.json records XLA:TPU's own accounting for the bench
programs (tools/aot_analyze.py, round 3). These tests keep the repo's
analytic claims honest against that record: if utils/flops.py or the
model architecture drifts, the compiler-vs-analytic ratio recorded in
the report no longer matches a freshly computed analytic figure and
this fails — prompting a report regeneration rather than silently
stale "ground truth".
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPORT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                      "docs", "aot_analysis.json")


def _jobs():
    with open(REPORT) as f:
        return json.load(f)["jobs"]


def test_headline_flops_matches_analytic_within_2pct():
    """The compiler counted the bf16/b16 step within 0.4% of the
    analytic model when the report was generated; a drift beyond 2%
    means flops.py or the architecture changed without regenerating.

    XLA's cost analysis prices an lhs-dilated conv at its effective
    FLOPs — the inserted zeros are free in the model even though the
    recorded program is the dense upsample. That convention equals the
    zeroskip algebra in utils/flops.py (dense counts the MACs the MXU
    executes on the dilated grid, +14.5G/gen-fwd), so the compiler pin
    compares against the effective accounting.
    """
    from cyclegan_tpu.config import Config, ModelConfig, TrainConfig
    from cyclegan_tpu.utils.flops import train_step_flops_per_image

    job = _jobs()["scan-headline-equivalent step/bf16/b16/256"]
    compiler_flops = job["cost_analysis"]["flops"]
    cfg = Config(model=ModelConfig(compute_dtype="bfloat16", image_size=256,
                                   upsample_impl="zeroskip"),
                 train=TrainConfig(batch_size=16))
    analytic = train_step_flops_per_image(cfg) * 2 * 16
    assert abs(compiler_flops / analytic - 1.0) < 0.02, (
        f"compiler {compiler_flops:.3e} vs analytic {analytic:.3e}: "
        "regenerate docs/aot_analysis.json (tools/aot_analyze.py) or fix "
        "utils/flops.py"
    )


def test_recorded_temps_fit_hbm_claims():
    """The 512² ledger claims: b4+remat fits 16G, b6 is at the edge."""
    jobs = _jobs()
    b4 = jobs["longctx step/bf16/b4/512/remat"]["memory_analysis"]
    b6 = jobs["longctx-oom-probe step/bf16/b6/512/remat"]["memory_analysis"]
    GiB = 2**30
    assert b4["temp_size_in_bytes"] < 12 * GiB
    assert b6["temp_size_in_bytes"] > b4["temp_size_in_bytes"]


def test_accum_temp_is_microbatch_bounded():
    """Grad-accum contract: accum-8×micro-1 temps within 10% of the
    plain micro-1 program (docs/BENCHMARKS.md, +4.4% when recorded)."""
    jobs = _jobs()
    accum = jobs["accum-probe step/bf16/accum8xmicro1/512"]["memory_analysis"]
    base = jobs["accum-baseline step/bf16/b1/512"]["memory_analysis"]
    ratio = accum["temp_size_in_bytes"] / base["temp_size_in_bytes"]
    assert ratio < 1.10, ratio  # equal-or-less is an improvement, not a bug


def test_multichip_payload_chip_count_invariant():
    """4-chip and 16-chip DP programs reduce the same payload — the
    scaling model's structural assumption."""
    jobs = _jobs()
    p4 = jobs["multichip step/bf16/b4x4/256/dp/2x2x1"]["collectives"]
    p16 = jobs["multichip step/bf16/b4x16/256/dp/4x4x1"]["collectives"]
    assert p4["payload_bytes_total"] == p16["payload_bytes_total"]
    assert p4["n_all_reduce"] == p16["n_all_reduce"] == 3
