"""Tests for cyclegan_tpu/resil/elastic.py: topology-aware slot
manifests, reshard-on-restore, global-batch decomposition, and
mid-epoch resume data positioning.

The invariant under test throughout: a checkpoint written on mesh A and
restored on mesh B must continue the SAME optimization trajectory —
value-identical parameters, the same global batch, and the exact next
sample in the data order. The end-to-end version of the same claim
(per-step loss equivalence across a real preemption) lives in
tools/chaos_drill.py elastic_resume, exercised here via its --fast
path.
"""

import dataclasses
import json
import os
import subprocess
import sys
import types

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from cyclegan_tpu.config import ParallelConfig, tiny_test_config  # noqa: E402
from cyclegan_tpu.data import build_data  # noqa: E402
from cyclegan_tpu.parallel.mesh import make_mesh_plan, replicated  # noqa: E402
from cyclegan_tpu.resil import elastic  # noqa: E402
from cyclegan_tpu.resil.faults import parse_spec  # noqa: E402
from cyclegan_tpu.utils.checkpoint import Checkpointer  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class Recorder:
    def __init__(self):
        self.events = []

    def event(self, kind, /, **fields):
        self.events.append(dict(fields, event=kind))

    def of(self, kind):
        return [e for e in self.events if e["event"] == kind]

    def flush(self):
        pass


def _plan(devices, n, spatial=1):
    return make_mesh_plan(
        ParallelConfig(spatial_parallelism=spatial), devices[:n])


def _config(tmp_path, batch_size=1, grad_accum=1):
    cfg = tiny_test_config()
    return dataclasses.replace(
        cfg, train=dataclasses.replace(
            cfg.train, output_dir=str(tmp_path), batch_size=batch_size,
            grad_accum=grad_accum))


def _state(plan):
    shard = replicated(plan)
    return {
        "g_params": jax.device_put(
            jnp.arange(24, dtype=jnp.float32).reshape(4, 6), shard),
        "opt": {"mu": jax.device_put(
            jnp.linspace(-1.0, 1.0, 12).reshape(3, 4), shard)},
        "step": jax.device_put(jnp.asarray(7, jnp.int32), shard),
    }


# ------------------------------------------------------- topology record


def test_topology_record_and_leaf_specs(devices, tmp_path):
    plan = _plan(devices, 8)
    config = _config(tmp_path, batch_size=2, grad_accum=3)
    state = _state(plan)
    rec = elastic.topology_record(plan, config, state=state)
    assert rec["n_data"] == 8 and rec["n_spatial"] == 1
    assert rec["global_batch_size"] == 8 * 2 * 3
    assert set(rec["leaf_specs"]) == {"g_params", "opt/mu", "step"}
    # Non-jax leaves degrade to 'host', never crash the manifest.
    specs = elastic.leaf_sharding_specs({"w": np.zeros(3)})
    assert specs == {"w": "host"}


def test_topology_matches_shape_only(devices):
    plan = _plan(devices, 8)
    assert elastic.topology_matches({"n_data": 8, "n_spatial": 1}, plan)
    assert not elastic.topology_matches({"n_data": 4, "n_spatial": 2}, plan)
    # Pre-elastic slots (no record) have nothing to reshard against.
    assert elastic.topology_matches(None, plan)


def test_save_meta_sidecar_roundtrip(devices, tmp_path):
    plan = _plan(devices, 4)
    config = _config(tmp_path, batch_size=2)
    ckpt = Checkpointer(str(tmp_path), keep=2)
    meta = elastic.save_meta(
        config, plan, state=_state(plan),
        mid_epoch={"epoch": 3, "step": 2, "data_seed": 99})
    ckpt.save(_state(plan), epoch=3, meta=meta)
    saved = elastic.read_sidecar_topology(str(tmp_path))
    assert saved["n_data"] == 4 and saved["global_batch_size"] == 8
    raw = json.load(open(os.path.join(str(tmp_path), "checkpoints",
                                      "meta.json")))
    assert raw["mid_epoch"] == {"epoch": 3, "step": 2, "data_seed": 99}


def test_read_sidecar_topology_absent_is_none(tmp_path):
    assert elastic.read_sidecar_topology(str(tmp_path)) is None


# ------------------------------------------- batch decomposition algebra


def _bd_cfg(b, a, spd=1):
    return types.SimpleNamespace(train=types.SimpleNamespace(
        batch_size=b, grad_accum=a, steps_per_dispatch=spd))


def _bd_plan(n_data):
    return types.SimpleNamespace(n_data=n_data)


@pytest.mark.parametrize("gbs,n_data,old,spd,want", [
    (8, 8, (1, 1), 1, (1, 1)),    # same mesh: untouched
    (8, 4, (1, 1), 1, (2, 1)),    # fewer shards: batch rescales
    (16, 4, (2, 2), 1, (2, 2)),   # configured pair already lands on gbs
    (12, 2, (4, 3), 1, (2, 3)),   # grad_accum (memory contract) kept
    (8, 2, (3, 3), 1, (1, 4)),    # neither side divides -> microbatch
    (8, 4, (1, 1), 2, (2, 1)),    # fused dispatch fine when accum == 1
])
def test_resolve_batch_decomposition(gbs, n_data, old, spd, want):
    saved = {"global_batch_size": gbs, "n_data": 8, "n_spatial": 1}
    got = elastic.resolve_batch_decomposition(
        saved, _bd_plan(n_data), _bd_cfg(*old, spd=spd))
    assert got == want
    assert n_data * got[0] * got[1] == gbs  # THE invariant


def test_resolve_batch_decomposition_refuses_indivisible():
    saved = {"global_batch_size": 6, "n_data": 6, "n_spatial": 1,
             "batch_size": 1, "grad_accum": 1}
    with pytest.raises(elastic.ElasticTopologyError,
                       match="spatial_parallelism"):
        elastic.resolve_batch_decomposition(
            saved, _bd_plan(4), _bd_cfg(1, 1))


def test_resolve_batch_decomposition_refuses_accum_vs_fused_dispatch():
    # per-shard batch 6 with grad_accum 4 and steps_per_dispatch 2:
    # accumulation is mutually exclusive with fused dispatch.
    saved = {"global_batch_size": 12, "n_data": 2, "n_spatial": 1}
    with pytest.raises(elastic.ElasticTopologyError,
                       match="steps_per_dispatch"):
        elastic.resolve_batch_decomposition(
            saved, _bd_plan(2), _bd_cfg(4, 4, spd=2))


def test_resolve_batch_decomposition_legacy_record_reconstructs_gbs():
    saved = {"n_data": 8, "batch_size": 2, "grad_accum": 1}
    assert elastic.resolve_batch_decomposition(
        saved, _bd_plan(4), _bd_cfg(1, 1)) == (4, 1)


def test_preflight_rewrites_config_only_on_topology_change(
        devices, tmp_path):
    src = _plan(devices, 8)
    config = _config(tmp_path, batch_size=1)
    ckpt = Checkpointer(str(tmp_path), keep=1)
    ckpt.save(_state(src), epoch=0,
              meta=elastic.save_meta(config, src, state=_state(src)))
    # Same topology: the user's batch choice stands, info is None.
    same, info = elastic.preflight_elastic(config, src)
    assert info is None and same is config
    # Halved data shards: batch doubles to preserve the global batch.
    dst = _plan(devices, 4)
    new, info = elastic.preflight_elastic(config, dst)
    assert info["changed"] and new.train.batch_size == 2
    assert dst.n_data * new.train.batch_size * new.train.grad_accum == 8


# --------------------------------------------------- reshard-on-restore


@pytest.mark.parametrize("src,dst", [
    ((8, 1), (4, 1)),   # dp8 -> dp4
    ((4, 1), (4, 2)),   # dp4 -> dp2 x sp2
    ((4, 2), (8, 1)),   # dp2 x sp2 -> dp8
])
def test_cross_topology_restore_value_identical(devices, tmp_path,
                                                src, dst):
    src_plan = _plan(devices, *src)
    dst_plan = _plan(devices, *dst)
    config = _config(tmp_path, batch_size=8 // src_plan.n_data)
    state = _state(src_plan)
    host_before = jax.tree.map(np.asarray, state)
    ckpt = Checkpointer(str(tmp_path), keep=2)
    ckpt.save(state, epoch=0,
              meta=elastic.save_meta(config, src_plan, state=state))

    config2, _ = elastic.preflight_elastic(config, dst_plan)
    rec = Recorder()
    template = _state(dst_plan)
    out = elastic.elastic_restore_if_exists(
        ckpt, template, dst_plan, config2, telemetry=rec)
    assert out.resumed and out.resharded and out.start_epoch == 1
    assert out.resume_step == 0 and out.data_seed is None
    # Value identity across the mesh change...
    host_after = jax.tree.map(np.asarray, out.state)
    for k in ("g_params", "step"):
        np.testing.assert_array_equal(host_after[k], host_before[k])
    np.testing.assert_array_equal(host_after["opt"]["mu"],
                                  host_before["opt"]["mu"])
    # ...placed under the DESTINATION mesh (template shardings).
    for leaf in jax.tree.leaves(out.state):
        assert leaf.sharding.mesh.shape == dst_plan.mesh.shape
    # ...with the global batch preserved by the preflight rewrite.
    assert (dst_plan.n_data * config2.train.batch_size
            * config2.train.grad_accum) == 8
    (ev,) = rec.of("elastic_reshard")
    assert ev["from_topology"]["n_data"] == src_plan.n_data
    assert ev["to_topology"]["n_data"] == dst_plan.n_data


def test_same_topology_restore_does_not_reshard(devices, tmp_path):
    plan = _plan(devices, 8)
    config = _config(tmp_path)
    state = _state(plan)
    ckpt = Checkpointer(str(tmp_path), keep=1)
    ckpt.save(state, epoch=2,
              meta=elastic.save_meta(config, plan, state=state))
    rec = Recorder()
    out = elastic.elastic_restore_if_exists(
        ckpt, _state(plan), plan, config, telemetry=rec)
    assert out.resumed and not out.resharded and out.start_epoch == 3
    assert rec.of("elastic_reshard") == []


def test_mid_epoch_record_surfaces_resume_position(devices, tmp_path):
    plan = _plan(devices, 8)
    config = _config(tmp_path)
    state = _state(plan)
    ckpt = Checkpointer(str(tmp_path), keep=2)
    ckpt.save(state, epoch=4,
              meta=elastic.save_meta(
                  config, plan, state=state,
                  mid_epoch={"epoch": 4, "step": 2, "data_seed": 77}))
    out = elastic.elastic_restore_if_exists(
        ckpt, _state(plan), plan, config)
    # The emergency slot re-ENTERS epoch 4 at step 2 with its data seed.
    assert (out.start_epoch, out.resume_step, out.data_seed) == (4, 2, 77)


def test_stale_mid_epoch_record_ignored_on_boundary_slot(
        devices, tmp_path):
    """A mid_epoch record for a DIFFERENT epoch than the restored slot
    (ring fallback to an older slot) must not teleport the resume."""
    plan = _plan(devices, 8)
    config = _config(tmp_path)
    state = _state(plan)
    ckpt = Checkpointer(str(tmp_path), keep=2)
    ckpt.save(state, epoch=4,
              meta=dict(
                  elastic.save_meta(config, plan, state=state),
                  mid_epoch={"epoch": 2, "step": 3, "data_seed": 5}))
    out = elastic.elastic_restore_if_exists(
        ckpt, _state(plan), plan, config)
    assert out.start_epoch == 5 and out.resume_step == 0


def test_cross_impl_restore_xla_to_halo_value_identical(devices, tmp_path):
    """8x1 (spatial_impl=xla) -> 2x4 (spatial_impl=halo) round-trip on a
    REAL tiny-model CycleGANState: the restored leaves are bit-identical,
    placed through the partition-rules table, and the restored params
    drive the explicit-halo generator to the same output the XLA path
    produces — checkpoints interchange across --spatial_impl."""
    from cyclegan_tpu.parallel.dp import shard_batch
    from cyclegan_tpu.train import build_models, create_state

    src_plan = _plan(devices, 8)                 # 8 x 1, XLA impl
    cfg = _config(tmp_path, batch_size=2)
    state = jax.device_put(
        create_state(cfg, jax.random.PRNGKey(0)), replicated(src_plan))
    host_before = jax.tree.map(np.asarray, state)
    ckpt = Checkpointer(str(tmp_path), keep=2)
    ckpt.save(state, epoch=0,
              meta=elastic.save_meta(cfg, src_plan, state=state))

    dst_plan = _plan(devices, 8, spatial=4)      # 2 x 4, halo impl
    cfg_h = dataclasses.replace(
        cfg,
        model=dataclasses.replace(cfg.model, spatial_impl="halo"),
        parallel=ParallelConfig(spatial_parallelism=4),
    )
    cfg2, _ = elastic.preflight_elastic(cfg_h, dst_plan)
    # global batch preserved across the topology change
    assert dst_plan.n_data * cfg2.train.batch_size * cfg2.train.grad_accum \
        == src_plan.n_data * cfg.train.batch_size
    template = create_state(cfg2, jax.random.PRNGKey(1))
    out = elastic.elastic_restore_if_exists(ckpt, template, dst_plan, cfg2)
    assert out.resumed and out.resharded

    for (pa, a), b in zip(
            jax.tree_util.tree_flatten_with_path(out.state)[0],
            jax.tree.leaves(host_before)):
        np.testing.assert_array_equal(
            np.asarray(a), b, err_msg=elastic._path_key(pa))
        assert a.sharding.mesh.shape == dst_plan.mesh.shape

    # The restored params run under BOTH impls on the destination mesh
    # and agree: the generator's halo shard_map path is a drop-in. (The
    # generator is the right probe at 32^2/spatial=4 — its stride-1
    # sites keep H_local >= the halo depth; the discriminator's 4x4
    # sites need spatial <= 2 here, covered by tests/test_spatial_impl.)
    gen_h, _ = build_models(cfg2, dst_plan)
    gen_x, _ = build_models(cfg, dst_plan)
    rng = np.random.RandomState(0)
    x = rng.rand(8, 32, 32, 3).astype(np.float32) * 2 - 1
    xs, _, _ = shard_batch(dst_plan, x, x, np.ones((8,), np.float32))
    out_h = jax.jit(gen_h.apply)(out.state.g_params, xs)
    out_x = jax.jit(gen_x.apply)(out.state.g_params, xs)
    np.testing.assert_allclose(
        np.asarray(out_h), np.asarray(out_x), atol=1e-5, rtol=0)


def test_cross_impl_restore_halo_to_xla_value_identical(devices, tmp_path):
    """Reverse seam: a slot written under spatial_impl=halo on 2x4
    restores value-identical onto a pure-DP 8x1 mesh under the XLA
    impl (param trees are identical by construction)."""
    from cyclegan_tpu.train import create_state

    src_plan = _plan(devices, 8, spatial=4)
    cfg_h = dataclasses.replace(
        _config(tmp_path, batch_size=4),
        parallel=ParallelConfig(spatial_parallelism=4),
    )
    cfg_h = dataclasses.replace(
        cfg_h, model=dataclasses.replace(cfg_h.model, spatial_impl="halo"))
    state = jax.device_put(
        create_state(cfg_h, jax.random.PRNGKey(2)), replicated(src_plan))
    host_before = jax.tree.map(np.asarray, state)
    ckpt = Checkpointer(str(tmp_path), keep=2)
    ckpt.save(state, epoch=0,
              meta=elastic.save_meta(cfg_h, src_plan, state=state))

    dst_plan = _plan(devices, 8)
    cfg_x = dataclasses.replace(
        _config(tmp_path, batch_size=1), parallel=ParallelConfig())
    cfg2, _ = elastic.preflight_elastic(cfg_x, dst_plan)
    template = create_state(cfg2, jax.random.PRNGKey(3))
    out = elastic.elastic_restore_if_exists(ckpt, template, dst_plan, cfg2)
    assert out.resumed and out.resharded
    for a, b in zip(jax.tree.leaves(out.state),
                    jax.tree.leaves(host_before)):
        np.testing.assert_array_equal(np.asarray(a), b)
        assert a.sharding.mesh.shape == dst_plan.mesh.shape


# ------------------------------------------------- mid-epoch data order


def test_mid_epoch_fast_forward_no_sample_skipped_or_repeated(
        tiny_config):
    """train_epoch(start_step=k) must yield EXACTLY batches k.. of the
    full epoch — same samples, same order, same padding weights."""
    data = build_data(tiny_config, global_batch_size=2)  # 4 steps/epoch
    full = list(data.train_epoch(3, prefetch=False))
    tail = list(data.train_epoch(3, prefetch=False, start_step=1))
    assert len(full) == data.train_steps
    assert len(tail) == data.train_steps - 1
    for (xa, ya, wa), (xb, yb, wb) in zip(full[1:], tail):
        np.testing.assert_array_equal(xa, xb)
        np.testing.assert_array_equal(ya, yb)
        np.testing.assert_array_equal(wa, wb)


def test_restore_seed_reproduces_saved_order(tiny_config):
    a = build_data(tiny_config, global_batch_size=2)
    a.reseed(2)  # a rollback bumped the seed before the emergency save
    saved_seed = a.seed
    first_a = next(iter(a.train_epoch(1, prefetch=False)))
    b = build_data(tiny_config, global_batch_size=2)
    b.restore_seed(saved_seed)
    assert b.seed == saved_seed
    first_b = next(iter(b.train_epoch(1, prefetch=False)))
    np.testing.assert_array_equal(first_a[0], first_b[0])


def test_preempt_fault_spec_parses():
    (f,) = parse_spec("preempt@step=5")
    assert f.kind == "preempt" and f.at == 5


def test_breaker_latches_on_local_request():
    guard = types.SimpleNamespace(requested_locally=False)
    br = elastic.MidEpochBreaker(guard)
    br.note(2)
    assert not br.should_break()
    guard.requested_locally = True
    assert br.should_break()
    guard.requested_locally = False  # latch survives flag churn
    assert br.should_break() and br.batches_done == 2


# ------------------------------------------------------------ e2e drill


def test_chaos_drill_elastic_resume_fast(tmp_path):
    """The acceptance drill: mid-epoch preempt on an 8-way data mesh,
    resume on 4x2 — per-step losses match the uninterrupted control
    across the seam within 1e-5, no sample skipped or repeated, the
    emergency save lands inside the deadline budget."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, "tools/chaos_drill.py", "--fast",
         "--only", "elastic_resume", "--workdir", str(tmp_path)],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=580)
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    report = json.loads(r.stdout.strip().splitlines()[-1])
    drill = report["drills"]["elastic_resume"]
    assert drill["pass"], drill
    assert drill["detail"]["seam_maxdiff"] <= 1e-5
