"""Training-run distributed tracing (cyclegan_tpu/obs/train_trace.py)
+ the collective probe (obs/collective_probe.py): span tiling on a real
2-epoch CPU run, the zero-extra-dispatch pin, the straggler drill via
an injected data_stall with data_wait blame, probe structural
determinism on a 2x1 host mesh, the Perfetto train-track schema
through tools/trace_timeline.py, the obs_report rollup, and the
no-sync static coverage of the new module.

The real-loop tests share ONE traced 2-epoch run (module fixture): the
tiling, reconciliation, Perfetto, and report assertions all read the
same stream, so the suite pays the compile cost once.
"""

import dataclasses
import json
import os
import sys
import time

import jax
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))

from cyclegan_tpu.config import ObsConfig, ParallelConfig  # noqa: E402
from cyclegan_tpu.obs import (  # noqa: E402
    StragglerDetector,
    TrainTracer,
    make_telemetry,
    probe_event_payload,
    reconcile,
    run_probe,
    tiling_error,
    trace_phase_sums,
)

HOP_NAMES = ("data_wait", "submit", "resolve", "host")


def _events(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


class ListLogger:
    """MetricsLogger-shaped capture for unit-level detector tests."""

    def __init__(self):
        self.events = []

    def event(self, kind, /, **fields):
        self.events.append({"event": kind, **fields})

    def flush(self):
        pass


def _build(config, devices, gb=4):
    from cyclegan_tpu.data import build_data
    from cyclegan_tpu.parallel import make_mesh_plan, shard_train_step
    from cyclegan_tpu.parallel.mesh import replicated
    from cyclegan_tpu.train import create_state, make_train_step

    plan = make_mesh_plan(config.parallel, devices[:4])
    data = build_data(config, gb)
    state = jax.device_put(create_state(config, jax.random.PRNGKey(0)),
                           replicated(plan))
    step = shard_train_step(plan, make_train_step(config, gb))
    return plan, data, state, step


# ------------------------------------------------- the shared traced run


@pytest.fixture(scope="module")
def traced_run(tiny_config, devices, tmp_path_factory):
    """A real 2-epoch fully-sampled traced run on the synthetic data:
    train + test pass per epoch, a collective_probe event mid-epoch 0,
    epoch rollups closing each trace. Returns (jsonl path, events)."""
    from cyclegan_tpu.parallel import shard_test_step
    from cyclegan_tpu.train import create_state, make_test_step
    from cyclegan_tpu.train import loop
    from cyclegan_tpu.utils.summary import NullSummary

    out = tmp_path_factory.mktemp("traced_run")
    path = str(out / "telemetry.jsonl")
    config = tiny_config
    gb = 4
    plan, data, state, train_step = _build(config, devices, gb)
    test_step = shard_test_step(plan, make_test_step(config, gb))
    tele = make_telemetry(
        ObsConfig(jsonl_path=path, train_trace_sample=1.0,
                  straggler_multiple=4.0),
        str(out))
    tele.manifest(config, plan=plan)
    summary = NullSummary()
    for epoch in range(2):
        t0 = time.perf_counter()
        state = loop.train_epoch(config, data, plan, train_step, state,
                                 summary, epoch=epoch, obs=tele)
        if epoch == 0:
            # The epoch-boundary probe: measured psum/ppermute seconds
            # reconciled against the analytic census, landing both as a
            # root instant on the open trace and in the goodput ledger.
            shapes = jax.eval_shape(
                lambda: create_state(config, jax.random.PRNGKey(0)))
            tele.event("collective_probe", **probe_event_payload(
                plan, config, gb, shapes, payloads_kb=(4,), repeats=2))
        results = loop.test_epoch(config, data, plan, test_step, state,
                                  summary, epoch=epoch, obs=tele)
        tele.epoch(epoch, elapse_s=time.perf_counter() - t0,
                   images_per_sec=16.0,
                   test_metrics={k: float(v) for k, v in results.items()})
    tele.close()
    return path, _events(path)


def _train_traces(events):
    return [e for e in events
            if e.get("event") == "trace" and e.get("name") == "train_epoch"]


def test_epoch_traces_tile_to_a_tenth_of_a_percent(traced_run):
    """The acceptance bound: on a REAL run, every level of the span
    graph tiles its parent within 0.1% — root children (passes +
    interludes) vs epoch wall, pass children (startup + dispatches) vs
    pass wall — because every boundary is the SAME timestamp seen from
    both sides, not a second clock read."""
    _, events = traced_run
    traces = _train_traces(events)
    assert len(traces) == 2
    for tr in traces:
        attrs = tr.get("attrs") or {}
        assert tr["status"] == "ok"
        assert attrs.get("tiling_complete") is True
        assert attrs.get("spans_dropped") == 0
        assert attrs.get("hop_sample") == 1.0
        assert tiling_error(tr) <= 0.001, tr["trace_id"]
        spans = tr["spans"]
        names = [s["name"] for s in spans]
        assert "train_pass" in names and "test_pass" in names
        # Fully sampled: every dispatch span has its hop children, and
        # they tile the dispatch wall exactly (rounding only).
        dispatches = [s for s in spans if s["name"] == "dispatch"]
        assert dispatches
        for d in dispatches:
            kids = [s for s in spans if s.get("parent") == d["id"]
                    and not (s.get("attrs") or {}).get("overlap")]
            assert sorted(s["name"] for s in kids) == sorted(HOP_NAMES)
            hop_sum = sum(s["t1"] - s["t0"] for s in kids)
            dur = d["t1"] - d["t0"]
            assert abs(hop_sum - dur) <= 1e-5 + 0.001 * dur
        # The device overlay rides concurrency and is marked as such.
        overlays = [s for s in spans if s["name"] == "device"]
        assert overlays
        assert all((s.get("attrs") or {}).get("overlap") for s in overlays)
        # The mid-epoch probe landed as a root instant on epoch 0.
    ev_names = [e["name"] for e in (traces[0].get("events") or [])]
    assert "collective_probe" in ev_names


def test_trace_phases_reconcile_with_goodput_ledger(traced_run):
    """The two pipelines read the SAME StepClock timestamps, so the
    span-derived phase sums and the goodput ledger's must agree within
    5% of the pass wall (the run_compare invariant): trace compute vs
    ledger compute+collective, data_wait vs data_wait, host vs
    host+compile (the ledger's residual is the one-sided slack)."""
    _, events = traced_run
    gp = {int(e["epoch"]): e for e in events if e["event"] == "goodput"}
    traces = _train_traces(events)
    assert gp and traces
    checked = 0
    for tr in traces:
        g = gp.get(int((tr.get("attrs") or {}).get("epoch")))
        if g is None:
            continue
        sums = trace_phase_sums(tr)
        ph = g["phases_s"]
        denom = float(g.get("passes_wall_s") or sums["passes_wall"])
        err = max(
            abs(sums["compute"] - (ph.get("compute", 0.0)
                                   + ph.get("collective", 0.0))),
            abs(sums["data_wait"] - ph.get("data_wait", 0.0)),
            abs(sums["host"] - (ph.get("host", 0.0)
                                + ph.get("compile", 0.0))),
        ) / max(denom, 1e-9)
        assert err <= 0.05, (g["epoch"], err, sums, ph)
        checked += 1
    assert checked >= 1
    # The probe upgraded the ledger's collective source on epoch 0.
    assert gp[0].get("comms_source") == "probe"


def test_tracing_adds_zero_dispatches_and_zero_fetches(
        tiny_config, devices, tmp_path, monkeypatch):
    """The overhead pin: the same epoch traced at sample 1.0 and fully
    untraced performs IDENTICAL device dispatches and device_get calls
    — the tracer is pure host arithmetic on timestamps the loop already
    takes."""
    from cyclegan_tpu.train import loop
    from cyclegan_tpu.utils.summary import NullSummary

    config = tiny_config
    counts = {}
    real_get = jax.device_get
    for label, obs_cfg in (
            ("untraced", ObsConfig(
                jsonl_path=str(tmp_path / "u.jsonl"),
                train_trace_sample=0.0, straggler_multiple=0.0)),
            ("traced", ObsConfig(
                jsonl_path=str(tmp_path / "t.jsonl"),
                train_trace_sample=1.0, straggler_multiple=4.0))):
        plan, data, state, base_step = _build(config, devices)
        n = {"dispatch": 0, "get": 0}

        def step_fn(state, xs, ys, ws, _f=base_step, _n=n):
            _n["dispatch"] += 1
            return _f(state, xs, ys, ws)

        def counting_get(x, _n=n):
            _n["get"] += 1
            return real_get(x)

        tele = make_telemetry(obs_cfg, str(tmp_path))
        if label == "traced":
            assert tele.train_tracer is not None
        else:
            assert tele.train_tracer is None
        monkeypatch.setattr(jax, "device_get", counting_get)
        try:
            loop.train_epoch(config, data, plan, step_fn, state,
                             NullSummary(), epoch=0, obs=tele)
        finally:
            monkeypatch.setattr(jax, "device_get", real_get)
        tele.epoch(0, elapse_s=1.0)
        tele.close()
        counts[label] = dict(n)
    assert counts["traced"] == counts["untraced"]
    assert counts["traced"]["dispatch"] > 0


# ------------------------------------------------------- straggler drill


def test_data_stall_drill_blames_data_wait(tiny_config, devices, tmp_path):
    """The drill the observatory exists for: a data_stall fault on the
    feed (absorbed by the loop's retry path, so the run SUCCEEDS) makes
    one dispatch's stage window balloon — the straggler detector must
    fire and blame data_wait, and the epoch trace must carry both the
    fault instant and the straggler census."""
    from cyclegan_tpu.resil.faults import FaultInjector
    from cyclegan_tpu.train import loop
    from cyclegan_tpu.utils.summary import NullSummary

    config = dataclasses.replace(
        tiny_config,
        # Enough dispatches to arm the rolling medians before the stall;
        # depth 0 keeps the retry sleep inside the stalled dispatch's
        # own stage window (crisp attribution).
        data=dataclasses.replace(tiny_config.data, synthetic_train_size=64),
        train=dataclasses.replace(tiny_config.train, prefetch_batches=0),
    )
    plan, data, state, step = _build(config, devices)
    path = str(tmp_path / "drill.jsonl")
    # The injected stall is the retry path's deterministic ~0.33 s of
    # backoff on top of a ~0.15 s median dispatch; 1.5x keeps the drill
    # robust to slow CI hosts (a noise-triggered straggler would blame
    # device/host and is filtered below).
    tele = make_telemetry(
        ObsConfig(jsonl_path=path, train_trace_sample=1.0,
                  straggler_multiple=1.5),
        str(tmp_path))
    inj = FaultInjector.from_spec("data_stall@step=10x3", telemetry=tele)
    state = loop.train_epoch(config, data, plan, step, state,
                             NullSummary(), epoch=0, obs=tele,
                             injector=inj)
    tele.epoch(0, elapse_s=1.0)
    tele.close()

    evs = _events(path)
    stragglers = [e for e in evs if e["event"] == "train_straggler"]
    assert stragglers, "no straggler fired on the injected stall"
    hits = [e for e in stragglers if e["blame"] == "data_wait"]
    assert hits, f"wrong blame: {[e['blame'] for e in stragglers]}"
    hit = hits[0]
    assert hit["split"] == "train" and hit["epoch"] == 0
    assert hit["wall_s"] > hit["multiple"] * hit["median_wall_s"]
    assert hit["components"]["data_wait"] > hit["medians"]["data_wait"]
    assert hit["excess_s"] > 0
    # The epoch trace absorbed the fault as a root instant and carries
    # the straggler census in its close attrs.
    (tr,) = _train_traces(evs)
    attrs = tr.get("attrs") or {}
    assert attrs.get("n_stragglers", 0) >= 1
    assert (attrs.get("straggler_blames") or {}).get("data_wait", 0) >= 1
    assert any(e["name"] == "fault_injected"
               for e in (tr.get("events") or []))
    # The absorbed retry is visible in the stream (the run recovered).
    assert any(e["event"] == "retry" and e["site"] == "data" for e in evs)


def test_straggler_detector_blame_is_componentwise():
    """Deterministic complement to the real drill: blame goes to the
    component with the largest excess over ITS OWN median, not just the
    biggest absolute number."""
    log = ListLogger()
    det = StragglerDetector(log, multiple=4.0)
    base = {"data_wait_s": 0.1, "fetch_block_s": 0.7,
            "dispatch_s": 0.05, "host_work_s": 0.05}
    for i in range(6):
        assert det.observe({"wall_s": 0.9, "dispatch": i, **base},
                           "train", 0) is None
    # Stage window balloons: data_wait blame even though device (0.7s)
    # is still the largest absolute component.
    blame = det.observe(
        {"wall_s": 4.9, "dispatch": 6, **dict(base, data_wait_s=4.1)},
        "train", 0)
    assert blame == "data_wait"
    # Fetch-block balloons: device blame.
    blame = det.observe(
        {"wall_s": 4.9, "dispatch": 7, **dict(base, fetch_block_s=4.7)},
        "train", 0)
    assert blame == "device"
    assert det.n_stragglers == 2
    assert det.blames == {"data_wait": 1, "device": 1}
    evs = [e for e in log.events if e["event"] == "train_straggler"]
    assert [e["blame"] for e in evs] == ["data_wait", "device"]
    for e in evs:
        assert set(e["components"]) == {"data_wait", "device", "host"}
        assert set(e["medians"]) == {"data_wait", "device", "host"}


def test_straggler_only_mode_emits_no_traces():
    """sample=0 with straggler watch on: the detector runs, trace spans
    don't — the knobs are independent."""
    log = ListLogger()
    tt = TrainTracer(log, sample=0.0, straggler_multiple=4.0)
    tt.pass_open(0, "train", 0.0)
    t = 0.0
    for i in range(7):
        wall = 10.0 if i == 6 else 1.0
        data_wait = 9.2 if i == 6 else 0.2
        rec = {"dispatch": i, "wall_s": wall, "stage_s": data_wait,
               "data_wait_s": data_wait, "dispatch_s": 0.1,
               "fetch_block_s": 0.5, "host_work_s": 0.2}
        tt.record(rec, t, t + data_wait + 0.1, t + wall)
        t += wall
    tt.pass_close({"wall_s": t}, t)
    assert tt.close_epoch(0) is False  # nothing was open
    kinds = [e["event"] for e in log.events]
    assert "trace" not in kinds
    assert kinds.count("train_straggler") == 1
    assert log.events[kinds.index("train_straggler")]["blame"] == "data_wait"


# ------------------------------------------------------ collective probe


def _strip_timings(probe):
    """Structural skeleton of a probe payload: everything except the
    measured seconds/bandwidths."""
    timing = {"baseline_s", "psum_s", "ppermute_s",
              "psum_gbps", "ppermute_gbps"}
    out = {k: v for k, v in probe.items() if k != "axes"}
    out["axes"] = {
        axis: {"size": a["size"],
               "buckets": [{k: v for k, v in b.items() if k not in timing}
                           for b in a["buckets"]]}
        for axis, a in probe["axes"].items()
    }
    return out


def test_collective_probe_structurally_deterministic_on_2x1(devices):
    """Two probes of the same 2x1 host mesh agree on everything that is
    not a measurement: axes, sizes, payload bytes, ring link bytes —
    the committed docs/collective_probe.json diffs cleanly round to
    round."""
    plan_mod = pytest.importorskip("cyclegan_tpu.parallel")
    plan = plan_mod.make_mesh_plan(ParallelConfig(spatial_parallelism=1),
                                   devices[:2])
    p1 = run_probe(plan, payloads_kb=(4, 64), repeats=2)
    p2 = run_probe(plan, payloads_kb=(4, 64), repeats=2)
    assert _strip_timings(p1) == _strip_timings(p2)
    assert p1["schema"] == 1 and p1["platform"] == "cpu"
    assert p1["mesh"] == {"n_data": 2, "n_spatial": 1, "n_devices": 2}
    (axis,) = p1["axes"]
    a = p1["axes"][axis]
    assert a["size"] == 2
    assert [b["payload_kb"] for b in a["buckets"]] == [4, 64]
    for b in a["buckets"]:
        assert b["payload_bytes"] == b["payload_kb"] * 1024
        # Ring all-reduce over n=2: 2(n-1)/n = 1.0x the payload.
        assert b["psum_link_bytes"] == pytest.approx(b["payload_bytes"])
        assert b["psum_s"] >= 0.0 and b["ppermute_s"] >= 0.0
        assert b["psum_gbps"] >= 0.0


def test_reconcile_prices_census_at_probed_bandwidth():
    """Pure arithmetic: census link bytes priced at the probe's
    measured Gbit/s, delta against the census's own link model."""
    probe = {"axes": {"data": {"size": 2, "buckets": [
        {"payload_kb": 4, "psum_gbps": 10.0, "ppermute_gbps": 5.0}]}}}
    census = {"per_link": {"data_allreduce_bytes": 1e9,
                           "spatial_bytes": 0.0},
              "link_gbps": 20.0}
    r = reconcile(probe, census)
    d = r["axes"]["data"]
    assert d["measured_s"] == pytest.approx(0.8)    # 1e9*8 / 10 Gbit/s
    assert d["est_s"] == pytest.approx(0.4)         # 1e9*8 / 20 Gbit/s
    assert d["delta_frac"] == pytest.approx(1.0)    # 2x slower than model
    assert r["measured_step_comms_s"] == pytest.approx(0.8)
    assert r["delta_frac"] == pytest.approx(1.0)
    # No census bytes for an axis -> it simply doesn't reconcile.
    assert "spatial" not in r["axes"]


# ----------------------------------------------- Perfetto + report tools


def test_trace_timeline_train_tracks_and_critical_path(traced_run,
                                                       tmp_path):
    import trace_timeline

    path, _ = traced_run
    out = tmp_path / "train.perfetto.json"
    assert trace_timeline.main([path, "--out", str(out), "--json"]) == 0
    doc = json.loads(out.read_text())
    names_by_tid = {ev["tid"]: ev["args"]["name"]
                    for ev in doc["traceEvents"]
                    if ev["ph"] == "M" and ev["name"] == "thread_name"}
    tracks = set(names_by_tid.values())
    assert {"train epochs", "train passes", "train dispatch",
            "train hops", "train device"} <= tracks
    slices = [ev for ev in doc["traceEvents"] if ev["ph"] == "X"]
    slice_names = {ev["name"] for ev in slices}
    assert {"epoch 0", "epoch 1", "train_pass", "test_pass",
            "dispatch", "data_wait", "device"} <= slice_names
    # Device overlays land on their own track, off the tiling tracks.
    tid_of = {v: k for k, v in names_by_tid.items()}
    assert any(ev["tid"] == tid_of["train device"]
               for ev in slices if ev["name"] == "device")

    traces = [t for t in trace_timeline.load_traces(path)
              if trace_timeline.is_train_trace(t)]
    table = trace_timeline.train_critical_path(traces)
    assert set(table) == {"epoch=0", "epoch=1"}
    for g in table.values():
        assert g["recon_frac"] is not None and g["recon_frac"] <= 0.001
        assert set(g["hops"]) >= {"train_pass", "test_pass", "dispatch",
                                  "data_wait", "submit", "resolve",
                                  "host", "device"}
    rendered = trace_timeline.render_table(table)
    assert "epoch=0" in rendered


def test_obs_report_training_sections(traced_run):
    import obs_report

    path, _ = traced_run
    events, skipped = obs_report.load_events(path)
    assert skipped == 0
    report = obs_report.fold(events, skipped)
    roll = report["train_trace_rollup"]
    assert roll["n_traces"] == 2
    assert set(roll["hops"]) >= {"dispatch", "data_wait", "submit",
                                 "device", "resolve", "host"}
    assert roll["spans_dropped"] == 0
    probe_roll = report["collective_probe_rollup"]
    assert probe_roll and probe_roll.get("axes")
    text = obs_report.render(report)
    assert "training traces" in text
    assert "per-step collective (measured)" in text
    assert "collective seconds source: probe" in text

    # Same stream minus the traces: the absent line names the knob.
    no_traces = [e for e in events if e.get("event") != "trace"]
    text2 = obs_report.render(obs_report.fold(no_traces))
    assert "training traces: absent" in text2
    assert "--train_trace_sample" in text2


# ------------------------------------------------------ static discipline


def test_no_sync_scan_covers_train_trace_module():
    from check_no_sync import hot_path_entries, run_check

    entries = dict(hot_path_entries())
    # The tracer derives spans from timestamps the clock already took:
    # zero sanctioned fetches allowed.
    assert entries.get("cyclegan_tpu/obs/train_trace.py") is False
    # The probe is the ONE obs/ module allowed to fence (its whole job
    # is timing collectives, off the hot path).
    assert entries.get("cyclegan_tpu/obs/collective_probe.py") is True
    assert run_check() == []
