"""Self-driving fleet (PR 12): the autoscaler state machine, the
brownout tier cascade + quality-probe budget loop, hedged dispatch with
the pop-time cancellation asymmetry, the p95 quarantine, and the
overload_brownout chaos drill end-to-end.

The decision cores (autoscale.Autoscaler, cascade.BrownoutController)
take a caller-supplied clock, so their state machines are tested with
synthetic signals and a fake `now` — no sleeps, no threads. The
integration tests drive a FleetExecutor over tests/test_fleet.py's
FakeEngine (control plane only, no XLA).
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from cyclegan_tpu.serve.fleet import (  # noqa: E402
    AutoscaleConfig,
    Autoscaler,
    BrownoutController,
    CascadeConfig,
    DEFAULT_CLASSES,
    DeadlineClass,
    FleetConfig,
    FleetExecutor,
    FleetSignals,
    QualityProbe,
    class_map,
)
from cyclegan_tpu.serve.fleet.admission import (  # noqa: E402
    AdmissionController,
    FleetRequest,
)
from tests.test_fleet import FakeEngine  # noqa: E402

CLASSES = class_map(DEFAULT_CLASSES)
BATCH = CLASSES["batch"]


def _sig(depth=0, drain=10.0, arrival=0.0, misses=0, circuits=0,
         n_active=1):
    return FleetSignals(queue_depth=depth, drain_rate=drain,
                        arrival_rate=arrival, deadline_misses=misses,
                        circuits_open=circuits, n_active=n_active)


class Recorder:
    def __init__(self):
        self._lock = threading.Lock()
        self.events = []

    def event(self, kind, **fields):
        with self._lock:
            self.events.append(dict(fields, event=kind))

    def of(self, kind):
        with self._lock:
            return [e for e in self.events if e["event"] == kind]


# -- autoscaler decision core ----------------------------------------------

def test_autoscale_config_validates():
    with pytest.raises(ValueError):
        AutoscaleConfig(min_replicas=0)
    with pytest.raises(ValueError):
        AutoscaleConfig(min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError):
        AutoscaleConfig(hysteresis=0)
    with pytest.raises(ValueError):
        AutoscaleConfig(up_arrival_ratio=1.0)
    with pytest.raises(ValueError):
        AutoscaleConfig(down_margin=0.5)


def test_hysteresis_requires_consecutive_overloaded_evals():
    a = Autoscaler(AutoscaleConfig(hysteresis=3, cooldown_s=0.0))
    hot = _sig(depth=50, drain=10.0)  # backlog 5s >> up_backlog_s
    assert a.observe(hot, now=0.0) is None
    assert a.observe(hot, now=0.1) is None
    # A single calm snapshot resets the streak — noise never scales.
    assert a.observe(_sig(depth=1, drain=100.0), now=0.2) is None
    assert a.observe(hot, now=0.3) is None
    assert a.observe(hot, now=0.4) is None
    assert a.observe(hot, now=0.5) == "up"
    # The decision consumed the streak: the very next eval starts over.
    assert a.observe(hot, now=0.6) is None


def test_cooldown_separates_scale_events():
    a = Autoscaler(AutoscaleConfig(hysteresis=1, cooldown_s=2.0,
                                   max_replicas=8))
    hot = _sig(depth=50, drain=10.0)
    assert a.observe(hot, now=0.0) == "up"
    # Still overloaded, but inside the cooldown window: hold.
    assert a.observe(hot, now=0.5) is None
    assert a.observe(hot, now=1.9) is None
    assert a.observe(hot, now=2.1) == "up"


def test_scale_up_stops_at_max_replicas():
    a = Autoscaler(AutoscaleConfig(hysteresis=1, cooldown_s=0.0,
                                   max_replicas=2))
    hot = _sig(depth=50, drain=10.0, n_active=2)
    assert a.observe(hot, now=0.0) is None


def test_arrival_outpacing_drain_counts_as_overload():
    a = Autoscaler(AutoscaleConfig(hysteresis=1, cooldown_s=0.0))
    # Tiny backlog, but arrivals are 2x drain with work queued.
    assert a.observe(_sig(depth=1, drain=100.0, arrival=200.0),
                     now=0.0) == "up"
    # An empty queue never scales up, whatever the rates say.
    a2 = Autoscaler(AutoscaleConfig(hysteresis=1, cooldown_s=0.0))
    assert a2.observe(_sig(depth=0, drain=100.0, arrival=200.0),
                      now=0.0) is None


def test_miss_delta_counts_as_overload():
    a = Autoscaler(AutoscaleConfig(hysteresis=1, cooldown_s=0.0))
    assert a.observe(_sig(misses=3), now=0.0) is None  # first = baseline
    assert a.observe(_sig(misses=3), now=0.1) is None  # no growth
    assert a.observe(_sig(misses=5), now=0.2) == "up"  # rollup grew


def test_scale_down_requires_idle_queue_and_margin():
    cfg = AutoscaleConfig(hysteresis=2, cooldown_s=0.0, down_margin=1.5)
    a = Autoscaler(cfg)
    # 3 active, arrival 10/s, drain 60/s: 2 replicas drain 40/s, margin
    # holds (10 * 1.5 < 40) -> idle streak builds to a down decision.
    idle = _sig(depth=0, drain=60.0, arrival=10.0, n_active=3)
    assert a.observe(idle, now=0.0) is None
    assert a.observe(idle, now=0.1) == "down"
    # At min_replicas the same signals hold steady instead.
    floor = _sig(depth=0, drain=60.0, arrival=10.0, n_active=1)
    a2 = Autoscaler(cfg)
    assert a2.observe(floor, now=0.0) is None
    assert a2.observe(floor, now=0.1) is None
    # Queued work vetoes scale-down outright.
    busy = _sig(depth=5, drain=60.0, arrival=10.0, n_active=3)
    a3 = Autoscaler(cfg)
    assert a3.observe(busy, now=0.0) is None
    assert a3.observe(busy, now=0.1) is None


def test_breaker_opening_suppresses_scale_up():
    """Circuit-breaker interaction: replicas DYING is not demand — a
    circuits_open increase must hold off scale-up for breaker_holdoff_s
    and reset the accumulated streak."""
    a = Autoscaler(AutoscaleConfig(hysteresis=2, cooldown_s=0.0,
                                   breaker_holdoff_s=5.0))
    hot = _sig(depth=50, drain=10.0)
    assert a.observe(hot, now=0.0) is None  # streak 1
    # A circuit opens: the capacity loss shows up as MORE backlog, but
    # scale-up must not chase it.
    assert a.observe(_sig(depth=80, drain=10.0, circuits=1),
                     now=0.1) is None
    assert a.observe(_sig(depth=80, drain=10.0, circuits=1),
                     now=1.0) is None
    assert a.observe(_sig(depth=80, drain=10.0, circuits=1),
                     now=4.0) is None
    # Holdoff expired (0.1 + 5.0): the pressure streak accumulated
    # while suppressed, so scale-up fires on the next evaluation.
    assert a.observe(_sig(depth=80, drain=10.0, circuits=1),
                     now=5.2) == "up"


# -- brownout cascade decision core ----------------------------------------

def _brownout(**over):
    kw = dict(tiers=("base", "int8", "perturb"), enter_backlog_s=0.2,
              exit_backlog_s=0.05, hysteresis=2, cooldown_s=0.0,
              shadow_fraction=0.25, quality_budget=0.05,
              widen_ratio=0.25, probe_cooldown_s=0.0)
    kw.update(over)
    cfg = CascadeConfig(**kw)
    return BrownoutController(cfg, cfg.tiers,
                              ["interactive", "batch", "best_effort"])


def test_brownout_plan_is_depth_first_per_class():
    b = _brownout()
    # 3 classes x 2 ladder steps: best_effort rides to the floor before
    # batch is touched; interactive degrades last of all.
    assert b.max_level == 6
    b._level = 2  # best_effort at the perturb floor
    assert b.tier_for("best_effort", "base") == "perturb"
    assert b.tier_for("batch", "base") == "base"
    assert b.tier_for("interactive", "base") == "base"
    b._level = 3  # batch takes its first step
    assert b.tier_for("batch", "base") == "int8"
    assert b.tier_for("interactive", "base") == "base"
    b._level = 6
    assert b.tier_for("interactive", "base") == "perturb"
    # Never upgrades: an explicit int8 request stays int8 at level 0,
    # and clamps at the floor rather than wrapping.
    b._level = 0
    assert b.tier_for("best_effort", "int8") == "int8"
    b._level = 2
    assert b.tier_for("best_effort", "int8") == "perturb"
    # Off-ladder tiers pass through untouched.
    assert b.tier_for("best_effort", "weird") == "weird"


def test_brownout_needs_two_available_tiers():
    cfg = CascadeConfig(tiers=("base", "int8"))
    with pytest.raises(ValueError):
        BrownoutController(cfg, ["base"], ["batch"])


def test_brownout_hysteresis_and_cooldown():
    b = _brownout(cooldown_s=1.0)
    assert b.update(backlog_s=0.5, now=0.0) is None   # streak 1
    assert b.update(backlog_s=0.5, now=0.1) == 1      # streak 2 -> raise
    # Cooling: pressure persists but the level holds for cooldown_s.
    assert b.update(backlog_s=0.5, now=0.5) is None
    assert b.update(backlog_s=0.5, now=0.8) is None
    assert b.update(backlog_s=0.5, now=1.2) == 2
    # Recovery path: sustained calm walks the level back down.
    assert b.update(backlog_s=0.01, now=2.3) is None
    assert b.update(backlog_s=0.01, now=2.4) == 1
    # Mid-band (between exit and enter) resets both streaks.
    assert b.update(backlog_s=0.1, now=3.5) is None
    assert b.update(backlog_s=0.01, now=3.6) is None
    assert b.update(backlog_s=0.01, now=3.7) == 0


def test_quality_probe_narrows_cap_and_level_clamps():
    b = _brownout()
    b.update(0.5, now=0.0)
    b.update(0.5, now=0.1)
    assert b.level == 1
    # A budget-blowing delta narrows the cap below the current level...
    assert b.note_probe(delta=0.5, now=0.2) == "narrow"
    for _ in range(10):
        b.note_probe(delta=0.5, now=0.3)
    assert b.snapshot()["quality_cap"] < b.max_level
    # ...and the next pressure tick clamps the level down immediately,
    # without waiting out a streak.
    caps = b.snapshot()["quality_cap"]
    if b.level > caps:
        assert b.update(0.5, now=0.4) == caps


def test_quality_probe_widens_cap_back_on_headroom():
    b = _brownout()
    assert b.note_probe(delta=0.5, now=0.0) == "narrow"
    assert b.snapshot()["quality_cap"] == b.max_level - 1
    # Sustained tiny deltas drag the EWMA under widen_ratio * budget
    # and the cap recovers step by step.
    verdicts = [b.note_probe(delta=0.0, now=1.0 + 0.1 * i)
                for i in range(20)]
    assert "widen" in verdicts
    assert b.snapshot()["quality_cap"] == b.max_level
    snap = b.snapshot()
    assert snap["n_narrowed"] >= 1 and snap["n_widened"] >= 1


def test_shadow_sampling_is_deterministic_one_in_n():
    b = _brownout(shadow_fraction=0.25)
    picks = [b.take_sample() for _ in range(12)]
    assert picks == [False, False, False, True] * 3
    b0 = _brownout(shadow_fraction=0.0)
    assert not any(b0.take_sample() for _ in range(8))


def test_quality_probe_thread_loop_over_fake_engine():
    """The full widen/narrow loop through the QualityProbe worker: a
    shadow re-run whose cheap output drifted past the budget narrows
    the cap; clean shadows widen it back."""
    b = _brownout(probe_cooldown_s=0.0)
    eng = FakeEngine(sizes=(32,), buckets=(1, 4))
    rec = Recorder()
    probe = QualityProbe(eng, b, logger=rec)
    try:
        img = np.random.RandomState(0).rand(32, 32, 3).astype(np.float32)
        # FakeEngine.run echoes its input, so the "full tier" output is
        # the image itself: cheap_fake = img + 1 is a delta of exactly 1.
        assert probe.submit(img, 32, "base", img + 1.0)
        deadline = time.perf_counter() + 10.0
        while time.perf_counter() < deadline and probe.n_run < 1:
            time.sleep(0.005)
        assert probe.n_run == 1
        assert b.snapshot()["quality_cap"] == b.max_level - 1
        # Clean shadows (delta 0) decay the EWMA under the widen
        # threshold and walk the cap back up step by step. Keep feeding
        # them until it fully recovers (the bounded inbox may drop).
        deadline = time.perf_counter() + 10.0
        while time.perf_counter() < deadline and \
                b.snapshot()["quality_cap"] < b.max_level:
            probe.submit(img, 32, "base", img.copy())
            time.sleep(0.005)
        assert b.snapshot()["quality_cap"] == b.max_level
        evs = rec.of("fleet_quality_probe")
        assert evs and evs[0]["verdict"] == "narrow"
        assert any(e["verdict"] == "widen" for e in evs)
    finally:
        assert probe.close()


# -- hedged dispatch -------------------------------------------------------

def test_hedge_ms_validates_against_deadline():
    with pytest.raises(ValueError):
        DeadlineClass("x", deadline_ms=100, shed_rank=0, hedge_ms=100)
    with pytest.raises(ValueError):
        DeadlineClass("x", deadline_ms=100, shed_rank=0, hedge_ms=0)
    k = DeadlineClass("x", deadline_ms=100, shed_rank=0, hedge_ms=50)
    assert k.hedge_ms == 50


def test_hedge_twin_shares_future_and_keeps_deadline():
    req = FleetRequest(np.zeros((32, 32, 3), np.float32), 32, "base",
                       BATCH, now=100.0)
    twin = req.twin()
    assert twin.future is req.future
    assert twin.is_hedge and not req.is_hedge
    assert twin.deadline == req.deadline
    assert twin.t_submit == req.t_submit


def test_resolved_elsewhere_copy_cancelled_at_pop():
    """A queued copy whose shared future already resolved must be
    dropped at pop time (won_elsewhere), not dispatched again."""
    adm = AdmissionController(capacity=8)
    req = FleetRequest(np.zeros((32, 32, 3), np.float32), 32, "base",
                       BATCH)
    adm.offer(req.twin())
    req.future.set_result({"fake": None})  # the primary won elsewhere
    batch = adm.next_batch(4, max_wait_s=0.0)
    assert batch == []
    assert adm.stats()["cancelled"] == {"won_elsewhere": 1}
    assert adm.depth == 0


def test_expired_hedge_twin_dies_silently_at_pop():
    """The expiry-asymmetry pin: an EXPIRED hedge twin is cancelled at
    pop WITHOUT failing the shared future — the primary (still in
    flight on a replica) alone serves late. Before the fix the twin
    took the expired-sheddable path and killed the caller's future
    while the primary was still computing."""
    adm = AdmissionController(capacity=8)
    past = time.perf_counter() - 10.0  # deadline long gone
    req = FleetRequest(np.zeros((32, 32, 3), np.float32), 32, "base",
                       BATCH, now=past)
    adm.offer(req.twin())
    batch = adm.next_batch(4, max_wait_s=0.0)
    assert batch == []
    assert not req.future.done()  # the primary still owns the outcome
    assert adm.stats()["cancelled"] == {"hedge_expired": 1}
    # Whereas an expired hedged PRIMARY (both copies lost to the queue,
    # e.g. after a crash re-enqueue) must still resolve the future —
    # conservatively failing it beats hanging the caller forever.
    primary = FleetRequest(np.zeros((32, 32, 3), np.float32), 32,
                           "base", BATCH, now=past)
    primary.hedged = True
    adm.offer(primary)
    assert adm.next_batch(4, max_wait_s=0.0) == []
    assert primary.future.done()


def test_hedge_fires_and_first_result_wins():
    """End-to-end over two FakeEngines: replica 0's engine stalls, the
    monitor hedges the in-flight request, the twin serves on replica 1,
    and the caller gets the twin's result while the stuck primary's
    later resolution is a no-op."""
    e0, e1 = FakeEngine(buckets=(1,)), FakeEngine(buckets=(1,))
    e0.gate = threading.Event()
    rec = Recorder()
    ex = FleetExecutor(
        e0,
        FleetConfig(n_replicas=2, max_wait_ms=1.0, health_poll_s=0.01,
                    hedge_ms=40.0),
        logger=rec, engines=[e0, e1])
    try:
        img = np.ones((32, 32, 3), np.float32)
        fut = ex.submit(img, klass="batch")
        assert fut.result(timeout=10.0)["fake"].shape == (32, 32, 3)
        st = ex.stats()
        assert st["hedges"]["dispatched"] == 1
        assert st["hedges"]["wins"] == 1
        assert rec.of("fleet_hedge")[0]["klass"] == "batch"
        e0.gate.set()  # release the stuck primary
        time.sleep(0.1)
    finally:
        summary = ex.close()
    assert summary["unjoined_replicas"] == []


# -- fleet integration: scale up / drain-before-retire / quarantine --------

def test_fleet_scales_up_and_drains_before_retire():
    """Overload a 1-replica fleet with a slow FakeEngine: the
    autoscaler must add a replica, and after the surge decays it must
    retire one — completing the retirement only once the victim
    surfaces free (no stranded futures, no lost requests)."""
    eng = FakeEngine(sizes=(32,), buckets=(1, 4), flush_s=0.02)
    rec = Recorder()
    ex = FleetExecutor(
        eng,
        FleetConfig(
            n_replicas=1, capacity=256, max_wait_ms=1.0,
            health_poll_s=0.01,
            autoscale=AutoscaleConfig(
                min_replicas=1, max_replicas=2, eval_s=0.05,
                hysteresis=2, cooldown_s=0.2, up_backlog_s=0.1)),
        logger=rec)
    img = np.zeros((32, 32, 3), np.float32)
    futs = []
    try:
        t_end = time.perf_counter() + 1.5
        while time.perf_counter() < t_end:
            futs.append(ex.submit(img, klass="best_effort"))
            time.sleep(0.002)
        deadline = time.perf_counter() + 10.0
        while time.perf_counter() < deadline and \
                not rec.of("fleet_autoscale"):
            time.sleep(0.01)
        ups = [e for e in rec.of("fleet_autoscale")
               if e["phase"] == "up"]
        assert ups, "fleet never scaled up under sustained backlog"
        # Decay: stop traffic; the fleet must come back down to 1, and
        # every submitted future must have resolved by then.
        deadline = time.perf_counter() + 20.0
        while time.perf_counter() < deadline and \
                ex.stats()["n_replicas_active"] > 1:
            time.sleep(0.02)
        # The active count drops at the "down" mark; the "retired"
        # completion event lands when the dispatcher next observes the
        # victim free — wait for it too, or this races under load.
        while time.perf_counter() < deadline and not any(
                e["phase"] == "retired"
                for e in rec.of("fleet_autoscale")):
            time.sleep(0.02)
        st = ex.stats()
        assert st["n_replicas_active"] == 1
        assert st["autoscale"]["scale_ups"] >= 1
        assert st["autoscale"]["scale_downs"] >= 1
        phases = [e["phase"] for e in rec.of("fleet_autoscale")]
        # Drain-before-retire is two distinct acts: the mark ("down")
        # and the dispatcher-side completion ("retired") once free.
        assert "down" in phases and "retired" in phases
        assert phases.index("down") < phases.index("retired")
        done = [f for f in futs if f.done()]
        assert len(done) == len(futs)
        assert all(f.exception() is None for f in done)
    finally:
        summary = ex.close()
    assert summary["unjoined_replicas"] == []


def test_quarantine_readmits_a_healed_replica():
    """Slot 1's engine runs 20x slower than slot 0's: its p95 detaches,
    it is quarantined and probed; once healed the probe lands under the
    bound recorded at quarantine time and the replica is readmitted."""
    fast, slow = FakeEngine(buckets=(1,)), FakeEngine(buckets=(1,))
    fast.flush_s, slow.flush_s = 0.005, 0.1
    rec = Recorder()
    ex = FleetExecutor(
        fast,
        FleetConfig(n_replicas=2, max_wait_ms=1.0, health_poll_s=0.01,
                    quarantine_multiple=3.0, quarantine_min_samples=4,
                    quarantine_probes=5,
                    quarantine_probe_interval_s=0.05),
        logger=rec, engines=[fast, slow])
    img = np.zeros((32, 32, 3), np.float32)
    try:
        deadline = time.perf_counter() + 15.0
        while time.perf_counter() < deadline and \
                not rec.of("fleet_quarantine"):
            ex.submit(img, klass="best_effort").result(timeout=10.0)
        quar = rec.of("fleet_quarantine")
        assert quar and quar[0]["action"] == "quarantine"
        assert quar[0]["replica"] == 1
        slow.flush_s = 0.0  # heal: the next probe must readmit
        deadline = time.perf_counter() + 15.0
        while time.perf_counter() < deadline and \
                ex.stats()["quarantine"]["readmitted"] < 1:
            time.sleep(0.02)
        st = ex.stats()
        assert st["quarantine"]["quarantined"] >= 1
        assert st["quarantine"]["readmitted"] >= 1
        assert st["quarantine"]["condemned"] == 0
        assert st["recoveries"] == 0
    finally:
        summary = ex.close()
    assert summary["unjoined_replicas"] == []


def test_quarantine_condemns_and_respawns_a_sick_replica():
    """A replica that stays slow burns its probe budget: condemned,
    stopped, and respawned through the SAME recovery path a crash
    takes (reason='quarantine')."""
    fast, slow = FakeEngine(buckets=(1,)), FakeEngine(buckets=(1,))
    fast.flush_s, slow.flush_s = 0.005, 0.15
    rec = Recorder()
    ex = FleetExecutor(
        fast,
        FleetConfig(n_replicas=2, max_wait_ms=1.0, health_poll_s=0.01,
                    quarantine_multiple=3.0, quarantine_min_samples=4,
                    quarantine_probes=2,
                    quarantine_probe_interval_s=0.03),
        logger=rec, engines=[fast, slow])
    img = np.zeros((32, 32, 3), np.float32)
    try:
        deadline = time.perf_counter() + 20.0
        while time.perf_counter() < deadline and \
                ex.stats()["quarantine"]["condemned"] < 1:
            try:
                ex.submit(img, klass="best_effort").result(timeout=10.0)
            except Exception:  # noqa: BLE001 — shed under churn is fine
                pass
        assert ex.stats()["quarantine"]["condemned"] >= 1
        # The monitor respawns the condemned slot on its next tick.
        deadline = time.perf_counter() + 10.0
        while time.perf_counter() < deadline and not any(
                e["reason"] == "quarantine"
                for e in rec.of("fleet_replica_down")):
            time.sleep(0.02)
        downs = rec.of("fleet_replica_down")
        assert any(e["reason"] == "quarantine" for e in downs)
        assert ex.stats()["recoveries"] >= 1
        actions = [e["action"] for e in rec.of("fleet_quarantine")]
        assert "condemn" in actions
    finally:
        slow.flush_s = 0.0
        summary = ex.close()
    assert summary["unjoined_replicas"] == []


# -- the acceptance drill --------------------------------------------------

def test_overload_brownout_drill_fast_passes():
    """tools/chaos_drill.py --fast --only overload_brownout: the
    scripted end-to-end — scale-up within bound, degrade-before-shed,
    zero interactive sheds with in-deadline p95, scale back down."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    env.pop("XLA_FLAGS", None)
    # The drill's pass bounds are timing-based (scale-up latency, p95
    # deadlines); one retry absorbs transient host contention while a
    # real regression still fails both attempts deterministically.
    for attempt in (0, 1):
        r = subprocess.run(
            [sys.executable, "tools/chaos_drill.py", "--fast",
             "--only", "overload_brownout"],
            cwd=REPO, env=env, capture_output=True, text=True,
            timeout=220)
        if r.returncode == 0:
            break
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
    report = json.loads(r.stdout.strip().splitlines()[-1])
    drill = report["drills"]["overload_brownout"]
    assert drill["pass"], drill["detail"]
    checks = drill["detail"]["checks"]
    for key in ("scale_up_within_bound", "degrade_before_shed",
                "zero_interactive_sheds", "interactive_p95_in_deadline",
                "scaled_back_down"):
        assert checks[key], (key, drill["detail"])
