"""The partition-rules table (parallel/mesh.py): the declarative layout
registry every sharding decision routes through.

Contract pinned here: every leaf path of a REAL model state resolves to
exactly ONE rule (the table is complete AND disjoint), activation names
resolve to the specs dp.py ships, and unknown paths fail at construction
with the path named — layout gaps must never silently land replicated.
"""

import re

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from cyclegan_tpu.config import ParallelConfig
from cyclegan_tpu.parallel import make_mesh_plan
from cyclegan_tpu.parallel.mesh import (
    activation_partition_rules,
    activation_spec,
    match_partition_rules,
    state_partition_rules,
    state_shardings,
    tree_path_key,
)
from cyclegan_tpu.train import create_state


@pytest.fixture(scope="module")
def spatial_plan():
    return make_mesh_plan(ParallelConfig(spatial_parallelism=2), jax.devices())


@pytest.fixture(scope="module")
def tiny_state(tiny_config):
    return create_state(tiny_config, jax.random.PRNGKey(0))


def _matching_rules(rules, path):
    return [name for name, pat, _ in rules if re.search(pat, path)]


def test_every_state_path_matches_exactly_one_rule(spatial_plan, tiny_state):
    rules = state_partition_rules(spatial_plan)
    flat = jax.tree_util.tree_flatten_with_path(tiny_state)[0]
    assert len(flat) > 100  # a real model, not a stub tree
    for path, _ in flat:
        key = tree_path_key(path)
        hits = _matching_rules(rules, key)
        assert len(hits) == 1, f"{key!r} matched {hits}"


def test_scanned_trunk_paths_resolve(tiny_config, spatial_plan):
    """The scan_blocks=True layout (stacked leaves under ScannedTrunk)
    must resolve through the same table."""
    import dataclasses

    cfg = tiny_config.replace(
        model=dataclasses.replace(tiny_config.model, scan_blocks=True)
    )
    state = create_state(cfg, jax.random.PRNGKey(0))
    rules = state_partition_rules(spatial_plan)
    for path, _ in jax.tree_util.tree_flatten_with_path(state)[0]:
        key = tree_path_key(path)
        hits = _matching_rules(rules, key)
        assert len(hits) == 1, f"{key!r} matched {hits}"


def test_activation_names_resolve_to_dp_specs(spatial_plan):
    assert activation_spec(spatial_plan, "x") == P("data", "spatial", None, None)
    assert activation_spec(spatial_plan, "weights") == P("data")
    assert activation_spec(spatial_plan, "xs") == P(
        None, "data", "spatial", None, None
    )
    assert activation_spec(spatial_plan, "ws") == P(None, "data")

    dp_plan = make_mesh_plan(ParallelConfig(), jax.devices())
    assert activation_spec(dp_plan, "x") == P("data")
    assert activation_spec(dp_plan, "xs") == P(None, "data")


def test_unknown_path_fails_naming_it(spatial_plan):
    with pytest.raises(ValueError, match="fc_head/lora_A"):
        match_partition_rules(
            state_partition_rules(spatial_plan), "fc_head/lora_A"
        )
    with pytest.raises(ValueError, match="latents"):
        activation_spec(spatial_plan, "latents")


def test_state_shardings_tree(spatial_plan, tiny_state):
    shardings = state_shardings(spatial_plan, tiny_state)
    flat_state = jax.tree_util.tree_leaves(tiny_state)
    flat_shard = jax.tree_util.tree_leaves(
        shardings, is_leaf=lambda x: hasattr(x, "spec")
    )
    assert len(flat_state) == len(flat_shard)
    for s in flat_shard:
        assert s.spec == P()  # the model's layout: replicated state

    # and the placements are usable: a device_put through the table
    # round-trips the state numerically
    placed = jax.device_put(tiny_state, shardings)
    np.testing.assert_allclose(
        np.asarray(jax.tree_util.tree_leaves(placed)[1]),
        np.asarray(flat_state[1]),
    )


def test_reshard_to_plan_uses_rules(spatial_plan, tiny_state):
    """elastic.reshard_to_plan routes CycleGANState placement through
    the table (no template needed) and yields donation-safe buffers."""
    from cyclegan_tpu.resil.elastic import reshard_to_plan

    out = reshard_to_plan(tiny_state, spatial_plan)
    for a, b in zip(
        jax.tree_util.tree_leaves(out), jax.tree_util.tree_leaves(tiny_state)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
        assert a.sharding.spec == P()


def test_activation_rules_cover_only_known_names(spatial_plan):
    names = [n for n, _, _ in activation_partition_rules(spatial_plan)]
    assert names == [
        "image_batch",
        "sample_weights",
        "stacked_image_batch",
        "stacked_sample_weights",
    ]
