"""True multi-process test of the multi-host path.

Spawns TWO processes, each with 2 virtual CPU devices, connected via
jax.distributed — exercising the real multi-host machinery the reference
lacks (SURVEY.md §2.3): global mesh spanning processes, per-process input
assembly (make_array_from_process_local_data), and collective-aligned
training. Both processes must report identical metrics, equal to a
single-process 4-device run of the same global batch.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _single_process_reference(n_devices=4, spatial=1):
    """The same two steps on this process's 8-device mesh restricted to
    n_devices, with the same data x spatial layout as the workers."""
    import dataclasses

    import jax

    from cyclegan_tpu.config import tiny_test_config
    from cyclegan_tpu.parallel import make_mesh_plan, shard_batch, shard_train_step
    from cyclegan_tpu.parallel.mesh import replicated
    from cyclegan_tpu.train import create_state, make_train_step

    config = tiny_test_config()
    config = dataclasses.replace(
        config,
        parallel=dataclasses.replace(config.parallel, spatial_parallelism=spatial),
    )
    plan = make_mesh_plan(config.parallel, jax.devices()[:n_devices])
    gb = plan.n_data
    state = create_state(config, jax.random.PRNGKey(0))
    state = jax.device_put(state, replicated(plan))
    step = shard_train_step(plan, make_train_step(config, gb))
    s = config.model.image_size
    rng = np.random.RandomState(0)
    for _ in range(2):
        x = rng.rand(gb, s, s, 3).astype(np.float32) * 2 - 1
        y = rng.rand(gb, s, s, 3).astype(np.float32) * 2 - 1
        w = np.ones((gb,), np.float32)
        xs, ys, ws = shard_batch(plan, x, y, w)
        state, metrics = step(state, xs, ys, ws)
    return {k: float(v) for k, v in jax.device_get(metrics).items()}


def _spawn_workers(port, local_devices=2, spatial=1):
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={local_devices}"
        env["TEST_COORD"] = f"127.0.0.1:{port}"
        env["TEST_NPROC"] = "2"
        env["TEST_PID"] = str(pid)
        env["TEST_LOCAL_DEVICES"] = str(local_devices)
        env["TEST_SPATIAL"] = str(spatial)
        procs.append(subprocess.Popen(
            [sys.executable, WORKER], cwd=REPO, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        ))
    return procs


# Cross-process collective setup (Gloo KV exchange, coordination-service
# barriers) has fixed ~30s handshake deadlines; on a loaded single-core
# host the second worker can simply not get scheduled in time. That is
# an environment failure, not a correctness failure — retry once.
_INIT_FLAKE_SIGNATURES = (
    "Gloo context initialization failed",
    "DEADLINE_EXCEEDED",
    "Barrier timed out",
)


def _collect_outputs_once(procs, last_failure):
    """communicate() both workers, parse the METRICS and FID lines every
    worker prints. Kills stragglers so a failed worker never leaks its
    coordinator port + JAX runtime. Returns None iff a worker died with
    the collective-init-starvation signature (recording its output in
    `last_failure` so exhausted retries still show real diagnostics)."""
    outs, fids = [], []
    try:
        for p in procs:
            out, err = p.communicate(timeout=600)
            if p.returncode != 0 and any(
                s in out + err for s in _INIT_FLAKE_SIGNATURES
            ):
                last_failure[:] = [out, err]
                return None
            assert p.returncode == 0, f"worker failed:\n{out}\n{err}"
            line = [l for l in out.splitlines() if l.startswith("METRICS ")]
            assert line, f"no METRICS line in:\n{out}"
            outs.append(json.loads(line[0][len("METRICS "):]))
            fid_line = [l for l in out.splitlines() if l.startswith("FID ")]
            assert fid_line, f"no FID line in:\n{out}"
            fids.append(json.loads(fid_line[0][len("FID "):]))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return outs, fids


def _run_workers(local_devices=2, spatial=1, retries=1):
    last_failure: list = ["", ""]
    for attempt in range(retries + 1):
        procs = _spawn_workers(_free_port(), local_devices, spatial)
        result = _collect_outputs_once(procs, last_failure)
        if result is not None:
            return result
        print(f"collective init starved (attempt {attempt + 1}); retrying")
    # Could be starvation OR a real desync that happens to hit the same
    # barrier deadlines — surface the last worker output so a regression
    # is debuggable rather than hidden behind 'host too loaded'.
    pytest.fail(
        "workers failed collective init on every attempt (loaded host? "
        "real desync?). Last worker output:\n"
        f"stdout:\n{last_failure[0][-3000:]}\nstderr:\n{last_failure[1][-3000:]}"
    )


@pytest.mark.slow
def test_two_process_training_matches_single_process(tmp_path):
    outs, fids = _run_workers()
    for fid in fids:
        # Sharded accumulation + cross-host allreduce == whole-set
        # statistics, on every host — bit-preserving f64 reduction,
        # so the moments agree to f64 roundoff, not f32 truncation.
        assert fid["n"] == [33, 37, 41]  # one count per accumulator
        assert fid["moment_err"] < 1e-12, fid
        assert abs(fid["fid_vs_whole"]) < 1e-2, fid

    # Both processes agree exactly (metrics are replicated global scalars).
    assert outs[0] == outs[1]

    # And match a single-process 4-device run of the same global batch.
    ref = _single_process_reference()
    assert set(ref) == set(outs[0])
    for k in ref:
        np.testing.assert_allclose(outs[0][k], ref[k], rtol=1e-5, err_msg=k)


@pytest.mark.slow
def test_two_process_four_device_spatial_mesh():
    """2 processes x 4 local devices = 8 global, 4x2 data x spatial mesh:
    halo-exchange spatial sharding composing with the cross-process
    runtime (VERDICT r1 asked for exactly this combination). Both
    processes must agree with each other and with a single-process
    8-device run of the same layout."""
    outs, _ = _run_workers(local_devices=4, spatial=2)
    assert outs[0] == outs[1]
    ref = _single_process_reference(n_devices=8, spatial=2)
    assert set(ref) == set(outs[0])
    for k in ref:
        np.testing.assert_allclose(outs[0][k], ref[k], rtol=1e-5, err_msg=k)
