"""True multi-process test of the multi-host path.

Spawns TWO processes, each with 2 virtual CPU devices, connected via
jax.distributed — exercising the real multi-host machinery the reference
lacks (SURVEY.md §2.3): global mesh spanning processes, per-process input
assembly (make_array_from_process_local_data), and collective-aligned
training. Both processes must report identical metrics, equal to a
single-process 4-device run of the same global batch.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _single_process_reference():
    """The same two steps on this process's 8-device mesh restricted to 4."""
    import jax

    from cyclegan_tpu.config import tiny_test_config
    from cyclegan_tpu.parallel import make_mesh_plan, shard_batch, shard_train_step
    from cyclegan_tpu.parallel.mesh import replicated
    from cyclegan_tpu.train import create_state, make_train_step

    config = tiny_test_config()
    plan = make_mesh_plan(config.parallel, jax.devices()[:4])
    state = create_state(config, jax.random.PRNGKey(0))
    state = jax.device_put(state, replicated(plan))
    step = shard_train_step(plan, make_train_step(config, 4))
    s = config.model.image_size
    rng = np.random.RandomState(0)
    for _ in range(2):
        x = rng.rand(4, s, s, 3).astype(np.float32) * 2 - 1
        y = rng.rand(4, s, s, 3).astype(np.float32) * 2 - 1
        w = np.ones((4,), np.float32)
        xs, ys, ws = shard_batch(plan, x, y, w)
        state, metrics = step(state, xs, ys, ws)
    return {k: float(v) for k, v in jax.device_get(metrics).items()}


@pytest.mark.slow
def test_two_process_training_matches_single_process(tmp_path):
    port = _free_port()
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        env["TEST_COORD"] = f"127.0.0.1:{port}"
        env["TEST_NPROC"] = "2"
        env["TEST_PID"] = str(pid)
        procs.append(subprocess.Popen(
            [sys.executable, WORKER], cwd=REPO, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        ))
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=600)
            assert p.returncode == 0, f"worker failed:\n{out}\n{err}"
            line = [l for l in out.splitlines() if l.startswith("METRICS ")]
            assert line, f"no METRICS line in:\n{out}"
            outs.append(json.loads(line[0][len("METRICS "):]))
            fid_line = [l for l in out.splitlines() if l.startswith("FID ")]
            assert fid_line, f"no FID line in:\n{out}"
            fid = json.loads(fid_line[0][len("FID "):])
            # Sharded accumulation + cross-host allreduce == whole-set
            # statistics, on every host — bit-preserving f64 reduction,
            # so the moments agree to f64 roundoff, not f32 truncation.
            assert fid["n"] == [33, 37, 41]  # one count per accumulator
            assert fid["moment_err"] < 1e-12, fid
            assert abs(fid["fid_vs_whole"]) < 1e-2, fid
    finally:
        # Never leak a live worker (it holds the coordinator port and two
        # JAX runtimes) when the other worker fails or times out.
        for p in procs:
            if p.poll() is None:
                p.kill()

    # Both processes agree exactly (metrics are replicated global scalars).
    assert outs[0] == outs[1]

    # And match a single-process 4-device run of the same global batch.
    ref = _single_process_reference()
    assert set(ref) == set(outs[0])
    for k in ref:
        np.testing.assert_allclose(outs[0][k], ref[k], rtol=1e-5, err_msg=k)
