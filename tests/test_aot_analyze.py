"""Report-merge semantics of tools/aot_analyze.py.

Each analysis job costs tens of minutes of XLA:TPU compile on this
host, so the merge rules protect measured data: partial runs add to the
report, failures never displace good entries.
"""

import importlib.util
import os
import sys

_TOOL = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "tools", "aot_analyze.py")
_spec = importlib.util.spec_from_file_location("aot_analyze", _TOOL)
aot_analyze = importlib.util.module_from_spec(_spec)
sys.modules["aot_analyze"] = aot_analyze
_spec.loader.exec_module(aot_analyze)

GOOD_A = {"config": {}, "compile_seconds": 1.0, "cost_analysis": {"flops": 1.0}}
GOOD_B = {"config": {}, "compile_seconds": 2.0}
FAIL = {"error": "Boom"}


def test_new_jobs_are_added():
    out = aot_analyze.merge_jobs({"a": GOOD_A}, {"b": GOOD_B})
    assert out == {"a": GOOD_A, "b": GOOD_B}


def test_fresh_success_replaces_prior_entry():
    newer = dict(GOOD_A, compile_seconds=9.0)
    out = aot_analyze.merge_jobs({"a": GOOD_A}, {"a": newer})
    assert out["a"]["compile_seconds"] == 9.0


def test_failure_does_not_displace_good_entry():
    out = aot_analyze.merge_jobs({"a": GOOD_A}, {"a": FAIL})
    assert out["a"] == GOOD_A


def test_failure_recorded_when_no_prior_or_prior_failed():
    assert aot_analyze.merge_jobs({}, {"a": FAIL})["a"] == FAIL
    newer_fail = {"error": "Other"}
    out = aot_analyze.merge_jobs({"a": FAIL}, {"a": newer_fail})
    assert out["a"] == newer_fail


def test_partial_run_keeps_unrun_jobs():
    out = aot_analyze.merge_jobs({"a": GOOD_A, "b": GOOD_B}, {"a": GOOD_A})
    assert set(out) == {"a", "b"}


def test_warm_rerun_preserves_cold_compile_seconds():
    """A cache-hit rerun (tiny compile_seconds) must not clobber the
    recorded cold figure: it survives as cold_compile_seconds."""
    prior = {"config": {}, "compile_seconds": 488.7}
    warm = {"config": {}, "compile_seconds": 2.9}
    out = aot_analyze.merge_jobs({"a": prior}, {"a": warm})
    assert out["a"]["compile_seconds"] == 2.9
    assert out["a"]["cold_compile_seconds"] == 488.7
    # and a later, even warmer rerun keeps the original cold figure
    out2 = aot_analyze.merge_jobs(out, {"a": {"config": {}, "compile_seconds": 1.1}})
    assert out2["a"]["cold_compile_seconds"] == 488.7
    # a slower (colder) rerun becomes the new reference
    out3 = aot_analyze.merge_jobs(out, {"a": {"config": {}, "compile_seconds": 600.0}})
    assert "cold_compile_seconds" not in out3["a"]
    assert out3["a"]["compile_seconds"] == 600.0
