"""EpochServices semantics: FIFO single-worker ordering, the barrier
completion contract, error containment, and inline execution after
close — the invariants the async epoch boundary (checkpoint commit,
plot rendering, FID) is built on."""

import threading

from cyclegan_tpu.utils.services import EpochServices


class _FakeTele:
    def __init__(self):
        self.events = []

    def event(self, kind, **kw):
        self.events.append((kind, kw))


def test_jobs_run_in_submission_order_and_barrier_waits():
    tele = _FakeTele()
    svc = EpochServices(telemetry=tele, echo=lambda *_: None)
    out = []
    gate = threading.Event()
    svc.submit("slow", lambda: (gate.wait(5), out.append("slow")))
    svc.submit("fast", out.append, "fast")
    assert svc.barrier(timeout=0.05) is False  # slow job still gated
    gate.set()
    assert svc.barrier(timeout=10) is True
    # Single worker: strict submission order, never interleaved.
    assert out == ["slow", "fast"]
    assert [k for k, _ in tele.events] == ["service_job", "service_job"]
    assert tele.events[0][1]["job"] == "slow"
    assert tele.events[0][1]["seconds"] >= 0
    assert svc.close(timeout=10) is True


def test_job_error_recorded_and_worker_survives():
    tele = _FakeTele()
    echoed = []
    svc = EpochServices(telemetry=tele, echo=echoed.append)
    svc.submit("boom", lambda: 1 / 0)
    out = []
    svc.submit("after", out.append, 1)
    assert svc.barrier(timeout=10)
    assert out == [1]  # the worker outlived the failing job
    assert len(svc.errors) == 1 and "ZeroDivisionError" in svc.errors[0]
    assert echoed and "boom" in echoed[0]
    kinds = [k for k, _ in tele.events]
    assert "service_error" in kinds and "service_job" in kinds
    svc.close(timeout=10)


def test_submit_after_close_runs_inline():
    svc = EpochServices(echo=lambda *_: None)
    assert svc.close(timeout=10)
    out = []
    svc.submit("late", out.append, "x")
    assert out == ["x"]  # ran synchronously; late exit work is not dropped
    assert svc.close(timeout=10)  # idempotent


def test_pending_counter_tracks_queue():
    svc = EpochServices(echo=lambda *_: None)
    gate = threading.Event()
    svc.submit("hold", gate.wait, 5)
    svc.submit("next", lambda: None)
    assert svc.pending >= 1
    gate.set()
    assert svc.barrier(timeout=10)
    assert svc.pending == 0
    svc.close(timeout=10)
