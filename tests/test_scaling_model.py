"""Analytic weak-scaling model (scaling_model.py): the pre-analysis for
BASELINE.md's >=90% @ v4-32/global-256 bar, checked for internal
consistency so the committed prediction can't drift from the code."""

import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest

import scaling_model


@pytest.fixture(scope="module")
def gbytes():
    return scaling_model.grad_bytes()


def test_grad_bytes_counts_all_four_trees(gbytes):
    """4 bytes/param over ~28.3M params (2 x 11.4M generators +
    2 x 2.77M discriminators, SURVEY.md §2.1) ~= 113 MB."""
    params = gbytes / 4
    assert 28.0e6 < params < 28.7e6


def test_v4_32_prediction_clears_baseline_bar(gbytes):
    out = scaling_model.predict(16, 16, "v4", bytes_per_step=gbytes)
    assert out["predicted_efficiency"] >= 0.98
    assert out["global_batch_pairs"] == 256


def test_bar_holds_with_10x_slower_ici(gbytes):
    """The committed claim: >=90% even at a 10x ICI derate — the margin
    statement in docs/BENCHMARKS.md."""
    out = scaling_model.predict(16, 16, "v4", link_gbps=4.5,
                                bytes_per_step=gbytes)
    assert out["predicted_efficiency"] >= 0.90


def test_efficiency_decreases_with_devices_and_bandwidth(gbytes):
    e8 = scaling_model.predict(8, 16, "v4", bytes_per_step=gbytes)
    e16 = scaling_model.predict(16, 16, "v4", bytes_per_step=gbytes)
    slow = scaling_model.predict(16, 16, "v4", link_gbps=1.0,
                                 bytes_per_step=gbytes)
    assert e8["predicted_efficiency"] > e16["predicted_efficiency"]
    assert e16["predicted_efficiency"] > slow["predicted_efficiency"]


def test_comm_time_is_ring_formula(gbytes):
    out = scaling_model.predict(16, 16, "v4", bytes_per_step=gbytes)
    expect_ms = 2 * (15 / 16) * gbytes / (2 * 45.0e9) * 1e3
    assert abs(out["t_comm_ms_no_overlap"] - expect_ms) < 0.01


def test_cli_emits_json_line():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "scaling_model.py"],
        capture_output=True, text=True, cwd=repo, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert r.returncode == 0, r.stderr[-2000:]
    line = json.loads(r.stdout.strip().splitlines()[-1])
    assert line["metric"] == "weak_scaling_efficiency_predicted"
    assert line["value"] >= 0.98


def _newest_onchip_record():
    """The newest committed official on-chip bench record (VERDICT r4
    item 9: keep drift guards pinned to the NEWEST record, not the
    oldest). Handles both record shapes: r3's builder capture is a list
    of {run, record} probe entries (warm run = official), r5+'s
    chip-autorun capture is the single driver-format dict from bench.py
    stdout (always a warm measurement — bench cold runs first)."""
    docs = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs")

    def round_num(name):
        # numeric sort: a future unpadded tag (r12 vs r05) must not
        # lose a lexicographic comparison to an older zero-padded one
        digits = "".join(c for c in name.split("_")[1] if c.isdigit())
        return int(digits) if digits else -1

    paths = sorted((p for p in os.listdir(docs)
                    if p.startswith("bench_r")
                    and p.endswith("_onchip.json")), key=round_num)
    assert paths, "no committed on-chip bench record"
    with open(os.path.join(docs, paths[-1])) as f:
        data = json.load(f)
    if isinstance(data, list):
        warm = [r["record"] for r in data
                if str(r.get("run", "")).startswith("warm")]
        assert warm, "no warm run in the on-chip record"
        return paths[-1], warm[-1]
    return paths[-1], data


def test_measured_ips_constant_matches_onchip_record():
    """VERDICT r3 weak #5 / r4 item 9: the scaling model's hard-coded
    measured throughput must not drift from the NEWEST committed
    on-chip record's scan/bfloat16/b16 row."""
    name, rec = _newest_onchip_record()
    assert rec["platform"] == "tpu", f"{name} is not a chip record"
    measured = rec["all"]["scan/bfloat16/b16"]
    assert abs(scaling_model.MEASURED_V5E_IPS - measured) <= 1.0, (
        f"MEASURED_V5E_IPS={scaling_model.MEASURED_V5E_IPS} drifted from "
        f"the newest on-chip record {name}: {measured}")
