"""Numerical cross-check of the Flax InceptionV3 pool3 port against an
independent torch implementation (tests/torch_inception.py).

Random weights (including random batch-norm running stats) flow through
tools/convert_inception_weights.py into the Flax model; both nets then
see the same inputs. Agreement at <=1e-4 pins every convention that can
silently diverge — stem VALID padding, factorized-7x7 padding,
count_include_pad=False averages, Mixed_7c's FID max-pool branch, the
OIHW->HWIO kernel transpose, and the BN eps/affine/running-stat wiring.
"""

import os
import sys

import numpy as np
import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
# Same import convention as test_inception_convert.py (top-level module
# from tools/), so one pytest session loads the converter exactly once.
sys.path.insert(0, os.path.join(_REPO, "tools"))

torch = pytest.importorskip("torch")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from convert_inception_weights import convert_state_dict  # noqa: E402
from cyclegan_tpu.eval.inception import InceptionV3Pool3, load_params_npz  # noqa: E402
from torch_inception import TorchInceptionPool3, randomize_  # noqa: E402


@pytest.fixture(scope="module")
def models(tmp_path_factory):
    tmodel = TorchInceptionPool3()
    randomize_(tmodel, seed=7)
    tmodel.eval()

    sd = {k: v.numpy() for k, v in tmodel.state_dict().items()}
    npz = convert_state_dict(sd)
    path = tmp_path_factory.mktemp("w") / "inception_oracle.npz"
    np.savez(path, **npz)

    net = InceptionV3Pool3()
    template = jax.eval_shape(
        lambda: net.init(jax.random.PRNGKey(0), jnp.zeros((1, 299, 299, 3)))
    )
    variables = load_params_npz(str(path), template)
    # One jitted apply shared by all tests (per-call lambdas would retrace
    # and recompile the full graph every time).
    apply = jax.jit(net.apply)
    return tmodel, apply, variables


def _features(models, x_nhwc: np.ndarray):
    tmodel, apply, variables = models
    with torch.no_grad():
        t_out = tmodel(torch.from_numpy(np.transpose(x_nhwc, (0, 3, 1, 2))))
    f_out = apply(variables, jnp.asarray(x_nhwc))
    return np.asarray(t_out), np.asarray(f_out)


def test_pool3_features_match_torch_oracle(models):
    rng = np.random.RandomState(0)
    x = (rng.rand(2, 299, 299, 3).astype(np.float32) * 2.0) - 1.0
    t_out, f_out = _features(models, x)
    assert t_out.shape == f_out.shape == (2, 2048)
    np.testing.assert_allclose(f_out, t_out, rtol=1e-4, atol=1e-4)


def test_pool3_match_on_structured_input(models):
    """Smooth gradient image (exercises border pixels differently from
    noise — SAME/VALID off-by-ones show up at borders first)."""
    yy, xx = np.mgrid[0:299, 0:299].astype(np.float32) / 299.0
    img = np.stack([yy, xx, (yy + xx) / 2.0], axis=-1) * 2.0 - 1.0
    x = img[None]
    t_out, f_out = _features(models, x)
    np.testing.assert_allclose(f_out, t_out, rtol=1e-4, atol=1e-4)
