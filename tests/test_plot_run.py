"""tools/plot_run.py: scalar read-back and curve rendering round trip."""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"
))

from plot_run import (  # noqa: E402
    plot,
    plot_health,
    read_health_events,
    read_scalars,
)


@pytest.fixture
def run_dir(tmp_path):
    from cyclegan_tpu.utils.summary import Summary

    s = Summary(str(tmp_path))
    for epoch in range(5):
        s.scalar("fid/G_vs_B", 1.0 / (epoch + 1), step=epoch)
        # Same tag through BOTH writers (exactly what the epoch loops do
        # with every loss scalar).
        s.scalar("loss_G/total", 2.0 - epoch * 0.1, step=epoch, training=True)
        s.scalar("loss_G/total", 3.0 - epoch * 0.1, step=epoch, training=False)
    s.close()
    return str(tmp_path)


def test_read_scalars_round_trip(run_dir):
    series = read_scalars(run_dir)
    assert "fid/G_vs_B" in series
    steps, values = zip(*series["fid/G_vs_B"])
    assert steps == (0, 1, 2, 3, 4)
    assert values[0] == pytest.approx(1.0) and values[4] == pytest.approx(0.2)


def test_train_and_test_writers_stay_separate(run_dir):
    """The test writer logs the SAME tags under <run>/test/; merging them
    into one series would render a meaningless zigzag of both curves."""
    series = read_scalars(run_dir)
    train = dict(series["loss_G/total"])
    test = dict(series["test/loss_G/total"])
    assert train[0] == pytest.approx(2.0)
    assert test[0] == pytest.approx(3.0)
    assert len(series["loss_G/total"]) == 5  # 5 points, not 10 interleaved


def test_plot_renders_matching_tags(run_dir, tmp_path):
    out = str(tmp_path / "curve.png")
    chosen = plot(read_scalars(run_dir), ["fid/.*"], out)
    assert chosen == ["fid/G_vs_B"]
    assert os.path.getsize(out) > 1000


def test_plot_unmatched_tags_fail_loudly(run_dir, tmp_path):
    with pytest.raises(SystemExit):
        plot(read_scalars(run_dir), ["nope/.*"], str(tmp_path / "x.png"))


def test_plot_health_renders_losses_envelopes_and_faults(tmp_path):
    """--jsonl mode: the committed flight-recorder fixture renders loss
    trajectories + per-network grad-norm envelopes, with its two
    health_fault events as markers."""
    fixture = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "data", "run_fail.jsonl")
    health, faults = read_health_events(fixture)
    assert len(health) == 3 and len(faults) == 2
    assert {e["kind"] for e in faults} == {"divergence", "d_collapse"}
    out = str(tmp_path / "health.png")
    n = plot_health(health, faults, out, title="fixture")
    # 4 loss terms + 4 network envelopes.
    assert n == 8
    assert os.path.getsize(out) > 1000


def test_plot_health_empty_stream_fails_loudly(tmp_path):
    empty = tmp_path / "empty.jsonl"
    empty.write_text('{"event": "manifest"}\nnot json\n')
    health, faults = read_health_events(str(empty))
    assert health == [] and faults == []
    with pytest.raises(SystemExit):
        plot_health(health, faults, str(tmp_path / "x.png"))


def test_pad_ab_report_runs_and_compares(run_dir, tmp_path, capsys,
                                         monkeypatch):
    """tools/pad_ab_report.py: end-to-end over Summary-written events —
    FID rows appear, MAE placeholders render, loss divergence vs the
    control computes over common epochs."""
    import pad_ab_report

    monkeypatch.setattr(sys, "argv", ["pad_ab_report.py", "--runs",
                                      f"control={run_dir}",
                                      f"variant={run_dir}"])
    pad_ab_report.main()
    out = capsys.readouterr().out
    assert "fid/G_vs_B" in out
    assert "MAE(X, F(G(X)))" in out
    # identical runs -> zero divergence on the shared loss tag
    assert "| `loss_G/total` | 0.0000 |" in out
