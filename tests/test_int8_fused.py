"""Inference-only fused int8 program tier: in-kernel dequant upsample
(ops/pallas/upsample_kernel.py), forward-only (no_vjp) kernel builds,
dtype-aware VMEM accounting (ops/pallas/vmem.py), the engine's
``int8_fused`` tier (ServeConfig(infer_tier=True)), and the brownout
ladder's fail-fast config validation.

Numerics contract: the fused kernel streams int8 weights and widens
INSIDE the kernel, applying each output channel's scale once after the
C_in reduction — the same sums as dequantize-then-convolve up to float
summation order, so parity gates at the repo's standard f32 bound
(1e-5). The no_vjp build path calls the SAME forward, so its outputs
are pinned bit-identical, not merely close.
"""

import dataclasses
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from cyclegan_tpu.config import GeneratorConfig, ModelConfig  # noqa: E402
from cyclegan_tpu.ops.pallas import vmem  # noqa: E402
from cyclegan_tpu.ops.pallas.epilogue_kernel import (  # noqa: E402
    instance_norm_relu_pad_pallas,
)
from cyclegan_tpu.ops.pallas.norm_kernel import (  # noqa: E402
    instance_norm_pallas,
)
from cyclegan_tpu.ops.pallas.upsample_kernel import (  # noqa: E402
    upsample_eligible,
    upsample_eligible_int8,
    upsample_norm_relu_pad_pallas,
    upsample_norm_relu_pad_pallas_int8,
)


def _rand(shape, seed=0, dtype=jnp.float32):
    k = jax.random.PRNGKey(seed)
    return (jax.random.normal(k, shape) * 2 + 0.5).astype(dtype)


def _quantize_kernel(kernel):
    """Per-output-channel symmetric int8, the engine's scheme."""
    from cyclegan_tpu.serve.engine import quantize_params_int8

    leaf = quantize_params_int8({"k": kernel})["k"]
    return leaf["int8_q"], leaf["int8_scale"]


# -- in-kernel dequant parity ----------------------------------------------

@pytest.mark.parametrize("shape,cout,pad", [
    ((1, 8, 8, 16), 8, 0),
    ((2, 7, 4, 8), 8, 0),
    ((1, 8, 8, 16), 8, 3),
])
def test_fused_int8_matches_dequant_outside(shape, cout, pad):
    """int8 weights widened inside the kernel produce the same result
    as dequantizing the weights first and running the f32 fused kernel
    — the scale distributes over the C_in sum, so the only difference
    is float summation order (same 1e-5 gate as f32 zeroskip parity,
    strictly tighter than the int8 tier's 0.05 end-to-end bound)."""
    x = _rand(shape, seed=0)
    kernel = _rand((3, 3, shape[-1], cout), seed=1) * 0.3
    scale = _rand((cout,), seed=2)
    bias = _rand((cout,), seed=3) * 0.1
    q, kscale = _quantize_kernel(kernel)
    assert q.dtype == jnp.int8
    dequant = q.astype(jnp.float32) * kscale.astype(jnp.float32)
    want = upsample_norm_relu_pad_pallas(
        x, dequant, scale, bias, pad=pad, interpret=True)
    got = upsample_norm_relu_pad_pallas_int8(
        x, q, kscale, scale, bias, pad=pad, interpret=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_fused_int8_rejects_non_int8_kernel():
    x = _rand((1, 8, 8, 16))
    kernel = _rand((3, 3, 16, 8))
    scale = bias = _rand((8,))
    with pytest.raises(TypeError, match="int8"):
        upsample_norm_relu_pad_pallas_int8(
            x, kernel, jnp.ones((1, 1, 1, 8)), scale, bias,
            interpret=True)


# -- forward-only (no_vjp) builds ------------------------------------------

def test_no_vjp_builds_are_bit_identical():
    """The no_vjp path skips custom-VJP registration but calls the SAME
    forward function, so outputs must match bit for bit — not within a
    tolerance. A drifted fused-tier program would silently eat the
    shadow-probe quality budget."""
    x = _rand((1, 8, 8, 16), seed=0)
    scale = _rand((16,), seed=1)
    bias = _rand((16,), seed=2) * 0.1
    a = instance_norm_pallas(x, scale, bias, interpret=True)
    b = instance_norm_pallas(x, scale, bias, interpret=True, no_vjp=True)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    a = instance_norm_relu_pad_pallas(x, scale, bias, pad=3,
                                      interpret=True)
    b = instance_norm_relu_pad_pallas(x, scale, bias, pad=3,
                                      interpret=True, no_vjp=True)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    kernel = _rand((3, 3, 16, 8), seed=3) * 0.3
    os_, ob = _rand((8,), seed=4), _rand((8,), seed=5) * 0.1
    a = upsample_norm_relu_pad_pallas(x, kernel, os_, ob, pad=0,
                                      interpret=True)
    b = upsample_norm_relu_pad_pallas(x, kernel, os_, ob, pad=0,
                                      interpret=True, no_vjp=True)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_norm_impl_accepts_fwd_variants():
    from cyclegan_tpu.ops.norm import instance_norm

    x = _rand((1, 8, 8, 16))
    scale, bias = _rand((16,)), _rand((16,)) * 0.1
    ref = instance_norm(x, scale, bias, impl="auto")
    got = instance_norm(x, scale, bias, impl="auto_fwd")
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


# -- dtype-aware VMEM accounting -------------------------------------------

def test_vmem_int8_accounting_charges_one_byte_per_weight():
    h, w, c_in, pad, item = 8, 8, 64, 0, 4
    f32 = vmem.upsample_bytes(h, w, c_in, pad, item)
    q = vmem.upsample_bytes_int8(h, w, c_in, pad, item)
    # Same activation slabs; the kernel term shrinks from 4 B to 1 B
    # per weight, plus one f32 scale row per output-channel block.
    kernel_elems = 9 * c_in * vmem.C_BLK
    assert f32 - q == kernel_elems * (item - 1) - vmem.C_BLK * 4


def test_int8_widens_the_eligibility_boundary():
    """The headline VMEM win: a bucket whose f32 weights overflow the
    budget fits once the kernel streams as int8. (32, 32, 1024) is the
    canonical straddle shape: f32 ~13.4 MB > budget, int8 ~9.8 MB."""
    h = w = 32
    c_in, pad, item = 1024, 0, 4
    assert vmem.upsample_fits(h, w, c_in, pad, item) is False
    assert vmem.upsample_fits_int8(h, w, c_in, pad, item) is True
    shape = (1, h, w, c_in)
    assert upsample_eligible(shape, jnp.float32, pad) is False
    assert upsample_eligible_int8(shape, jnp.float32, pad) is True
    # Everything f32-eligible stays int8-eligible (monotone win).
    small = (1, 8, 8, 16)
    assert upsample_eligible(small, jnp.float32, 0)
    assert upsample_eligible_int8(small, jnp.float32, 0)
    # Degenerate geometry still refuses.
    assert vmem.upsample_fits_int8(0, 8, 16, 0, item) is False
    assert vmem.upsample_fits_int8(8, 8, 16, -1, item) is False


def test_fused_int8_ineligible_shape_raises():
    # Far past even the int8 budget: accounting, not geometry.
    shape = (1, 64, 64, 4096)
    assert not upsample_eligible_int8(shape, jnp.float32, 0)
    x = _rand((1, 4, 4, 8))
    q = jnp.zeros((3, 3, 8, 4), jnp.int8)
    with pytest.raises(NotImplementedError):
        upsample_norm_relu_pad_pallas_int8(
            jnp.zeros(shape, jnp.float32), jnp.zeros(
                (3, 3, 4096, 4), jnp.int8), jnp.ones((1, 1, 1, 4)),
            jnp.ones((4,)), jnp.zeros((4,)))
    del x, q


# -- engine tier -----------------------------------------------------------

def _tiny_model_cfg():
    return ModelConfig(
        generator=GeneratorConfig(filters=4, num_residual_blocks=1),
        image_size=16,
        compute_dtype="float32",
    )


@pytest.fixture(scope="module")
def fused_engine():
    from cyclegan_tpu.serve.engine import (
        InferenceEngine,
        ServeConfig,
        build_generator,
    )

    cfg = _tiny_model_cfg()
    gen = build_generator(cfg)
    params = gen.init(jax.random.PRNGKey(0),
                      jnp.zeros((1, 16, 16, 3), jnp.float32))
    return InferenceEngine(
        cfg, params,
        serve_cfg=ServeConfig(batch_buckets=(2,), sizes=(16,),
                              dtype="float32", int8_tier=True,
                              infer_tier=True))


def test_fused_tier_compiles_and_tracks_base(fused_engine):
    eng = fused_engine
    assert eng.tiers == ("base", "int8", "int8_fused")
    assert set(eng.programs_int8_fused) == set(eng.programs)
    assert eng.resolve_tier("int8_fused") == "int8_fused"
    x = np.random.RandomState(1).uniform(
        -1, 1, (2, 16, 16, 3)).astype(np.float32)
    base = np.asarray(eng.run(x, size=16)[0][0])
    int8 = np.asarray(eng.run(x, size=16, tier="int8")[0][0])
    fused = np.asarray(eng.run(x, size=16, tier="int8_fused")[0][0])
    assert fused.dtype == np.float32
    assert np.all(np.isfinite(fused))
    # Same end-to-end quality budget as the int8 tier (weight-only
    # quantization over a tanh-bounded trunk)...
    assert float(np.max(np.abs(fused - base))) < 0.05
    # ...and the fused program computes the SAME quantized math as the
    # dequant-outside int8 program up to summation order, so the two
    # tiers sit orders of magnitude closer to each other than either
    # sits to f32.
    assert float(np.max(np.abs(fused - int8))) < 1e-5


def test_fused_tier_shares_one_quantized_tree(fused_engine):
    # int8 and int8_fused run off the SAME quantized params — the
    # fused tier adds programs, not a second copy of the weights.
    assert fused_engine._fwd_params_int8 is not None


def test_engine_without_infer_tier_rejects_fused_requests():
    from cyclegan_tpu.serve.engine import (
        InferenceEngine,
        ServeConfig,
        build_generator,
    )

    cfg = _tiny_model_cfg()
    gen = build_generator(cfg)
    params = gen.init(jax.random.PRNGKey(0),
                      jnp.zeros((1, 16, 16, 3), jnp.float32))
    eng = InferenceEngine(
        cfg, params,
        serve_cfg=ServeConfig(batch_buckets=(1,), sizes=(16,),
                              dtype="float32"))
    with pytest.raises(ValueError, match="infer_tier"):
        eng.resolve_tier("int8_fused")


def test_infer_tier_refuses_fused_cycle():
    from cyclegan_tpu.serve.engine import ServeConfig

    with pytest.raises(ValueError, match="infer_tier"):
        ServeConfig(with_cycle=True, infer_tier=True)


def test_fleet_executor_e2e_int8_fused(fused_engine):
    from cyclegan_tpu.serve.fleet import FleetConfig, FleetExecutor

    fleet = FleetExecutor(fused_engine, FleetConfig(
        n_replicas=1, max_batch=2, max_wait_ms=1.0))
    try:
        assert "int8_fused" in fleet.stats()["tiers"]
        img = np.random.RandomState(2).uniform(
            -1, 1, (16, 16, 3)).astype(np.float32)
        out = fleet.submit(img, tier="int8_fused").result(timeout=60)
        want = np.asarray(fused_engine.run(
            img[None], size=16, tier="int8_fused")[0][0])[0]
        np.testing.assert_allclose(np.asarray(out["fake"]), want,
                                   rtol=1e-5, atol=1e-5)
    finally:
        fleet.close()


# -- brownout ladder: fused rung + fail-fast config ------------------------

def test_cascade_steps_through_fused_rung():
    from cyclegan_tpu.serve.fleet.cascade import (
        BrownoutController,
        CascadeConfig,
    )

    cfg = CascadeConfig(tiers=("base", "int8", "int8_fused"))
    b = BrownoutController(cfg, cfg.tiers,
                           ["interactive", "batch", "best_effort"])
    assert b.max_level == 6  # 3 classes x 2 ladder steps
    b._level = 1
    assert b.tier_for("best_effort", "base") == "int8"
    b._level = 2
    assert b.tier_for("best_effort", "base") == "int8_fused"
    assert b.tier_for("batch", "base") == "base"
    b._level = 6
    assert b.tier_for("interactive", "base") == "int8_fused"
    # An explicit int8 request degrades one rung, to the fused floor.
    b._level = 2
    assert b.tier_for("best_effort", "int8") == "int8_fused"


def test_fleet_config_rejects_unknown_degrade_order_class():
    from cyclegan_tpu.serve.fleet import FleetConfig
    from cyclegan_tpu.serve.fleet.cascade import CascadeConfig

    with pytest.raises(ValueError, match="platinum") as ei:
        FleetConfig(cascade=CascadeConfig(
            tiers=("base", "int8"),
            degrade_order=("best_effort", "platinum")))
    # Domain-registry-style refusal: the error names the valid set.
    for name in ("interactive", "batch", "best_effort"):
        assert name in str(ei.value)


def test_fleet_executor_rejects_uncompiled_cascade_tier(fused_engine):
    from cyclegan_tpu.serve.fleet import FleetConfig, FleetExecutor
    from cyclegan_tpu.serve.fleet.cascade import CascadeConfig

    # The real fused engine never compiled "perturb": asking the ladder
    # to degrade into it must fail at construction, naming both sides.
    with pytest.raises(ValueError, match="perturb") as ei:
        FleetExecutor(fused_engine, FleetConfig(cascade=CascadeConfig(
            tiers=("base", "int8", "perturb"))))
    assert "int8_fused" in str(ei.value)  # ...have [compiled tiers]


# -- cache_warm coverage ---------------------------------------------------

def test_cache_warm_lists_fused_programs():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    from tools.cache_warm import serve_programs

    progs = serve_programs()
    fused = [p for p in progs if p.get("quantized") == "fused"]
    assert fused, "no int8_fused rows in the warm list"
    keys = [p["key"] for p in progs]
    assert len(keys) == len(set(keys))
    for p in fused:
        assert p["dtype"] == "float32"
        assert any(c.startswith("serve/int8_fused/") for c in p["covers"])
