"""Inference CLI: translate a folder of images with a trained checkpoint
(framework extension — the reference has no inference entry point)."""

import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_translate_cli(tmp_path):
    from PIL import Image

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)

    # 1) Train one tiny epoch to produce a checkpoint.
    run_dir = tmp_path / "run"
    r = subprocess.run(
        [sys.executable, "main.py", "--output_dir", str(run_dir),
         "--epochs", "1", "--batch_size", "2", "--verbose", "0",
         "--data_source", "synthetic", "--image_size", "32",
         "--synthetic_train_size", "2", "--synthetic_test_size", "2"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=900,
    )
    assert r.returncode == 0, r.stderr

    # 2) Translate a 3-image folder (batch 2 -> exercises ragged padding).
    src = tmp_path / "in"
    src.mkdir()
    rng = np.random.RandomState(0)
    for i in range(3):
        Image.fromarray(rng.randint(0, 255, (40, 48, 3), np.uint8)).save(
            src / f"im{i}.jpg"
        )
    out = tmp_path / "out"
    r2 = subprocess.run(
        [sys.executable, "translate.py", "--output_dir", str(run_dir),
         "--input", str(src), "--output", str(out), "--image_size", "32",
         "--batch_size", "2", "--panels"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=900,
    )
    assert r2.returncode == 0, f"stdout:\n{r2.stdout}\nstderr:\n{r2.stderr}"
    for i in range(3):
        im = Image.open(out / f"im{i}.png")
        assert im.size == (32, 32)
        panel = Image.open(out / f"im{i}_panel.png")
        assert panel.size == (96, 32)  # [input | translated | cycled]

    # 3) Missing checkpoint -> clean error.
    r3 = subprocess.run(
        [sys.executable, "translate.py", "--output_dir", str(tmp_path / "none"),
         "--input", str(src), "--output", str(out), "--image_size", "32"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300,
    )
    assert r3.returncode != 0
    assert "no checkpoint" in r3.stderr
