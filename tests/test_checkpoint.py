"""Checkpoint/resume tests: single-slot overwrite, auto-resume gate,
epoch counter survives (improving on reference main.py:148-170 which
restarts epochs at 0)."""

import jax
import jax.numpy as jnp
import numpy as np

from cyclegan_tpu.train import create_state, make_train_step
from cyclegan_tpu.utils.checkpoint import Checkpointer


def _tree_equal(a, b):
    return all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
    )


def test_save_restore_roundtrip(tiny_config, tmp_path):
    state = create_state(tiny_config, jax.random.PRNGKey(0))
    ckpt = Checkpointer(str(tmp_path))
    ckpt.save(state, epoch=7)
    restored, next_epoch = ckpt.restore(jax.eval_shape(lambda: state))
    assert next_epoch == 8
    assert _tree_equal(state.g_params, restored.g_params)
    assert _tree_equal(state.dy_opt, restored.dy_opt)


def test_auto_resume_gate(tiny_config, tmp_path):
    state = create_state(tiny_config, jax.random.PRNGKey(0))
    ckpt = Checkpointer(str(tmp_path))
    # no checkpoint yet: returns template, epoch 0, resumed=False
    out, epoch, resumed = ckpt.restore_if_exists(state)
    assert not resumed and epoch == 0 and out is state
    ckpt.save(state, epoch=0)
    out, epoch, resumed = ckpt.restore_if_exists(state)
    assert resumed and epoch == 1


def test_single_slot_overwrite(tiny_config, tmp_path):
    cfg = tiny_config
    state = create_state(cfg, jax.random.PRNGKey(0))
    ckpt = Checkpointer(str(tmp_path))
    ckpt.save(state, epoch=0)

    # Advance one step and overwrite the slot.
    s = cfg.model.image_size
    x = np.random.RandomState(0).rand(2, s, s, 3).astype(np.float32) * 2 - 1
    step = jax.jit(make_train_step(cfg, 2))
    state2, _ = step(state, jnp.asarray(x), jnp.asarray(x), jnp.ones((2,), jnp.float32))
    ckpt.save(state2, epoch=5)

    restored, next_epoch = ckpt.restore(state)
    assert next_epoch == 6
    assert int(restored.step) == 1
    assert not _tree_equal(state.g_params, restored.g_params)
    assert _tree_equal(state2.g_params, restored.g_params)


def test_async_save_roundtrips_after_barrier(tiny_config, tmp_path):
    """save(services=...) moves the commit barrier + sidecar off the
    caller thread; after barrier() the slot must be complete and the
    epoch counter correct — the async-checkpoint completion contract."""
    from cyclegan_tpu.utils.services import EpochServices

    state = create_state(tiny_config, jax.random.PRNGKey(0))
    ckpt = Checkpointer(str(tmp_path))
    svc = EpochServices(echo=lambda *_: None)
    ckpt.save(state, epoch=4, meta={"tag": "async"}, services=svc)
    assert svc.barrier(timeout=120)
    assert not svc.errors
    restored, next_epoch = ckpt.restore(jax.eval_shape(lambda: state))
    assert next_epoch == 5
    assert ckpt.read_meta()["tag"] == "async"
    assert _tree_equal(state.g_params, restored.g_params)
    svc.close(timeout=10)


class _GatedCkptr:
    """Stand-in Orbax checkpointer whose commit barrier blocks until the
    test releases it — makes the sidecar ordering observable."""

    def __init__(self):
        self.gate = __import__("threading").Event()
        self.wait_calls = 0

    def save(self, path, state, force=True):
        pass

    def wait_until_finished(self):
        self.wait_calls += 1
        assert self.gate.wait(10)

    def close(self):
        pass


def test_async_sidecar_written_only_after_commit_barrier(tmp_path):
    """meta.json pairs an epoch with a COMMITTED slot. If it were
    written before wait_until_finished, a crash mid-commit could leave
    a fresh sidecar pointing at a torn/previous slot and auto-resume
    would skip re-running the lost epoch."""
    import os
    import time

    from cyclegan_tpu.utils.services import EpochServices

    ckpt = Checkpointer(str(tmp_path))
    gated = _GatedCkptr()
    ckpt._ckptr = gated
    svc = EpochServices(echo=lambda *_: None)
    ckpt.save({"w": 1}, epoch=9, services=svc)
    # save() returned, but the commit is gated: no sidecar may exist yet.
    deadline = time.monotonic() + 1.0
    while time.monotonic() < deadline and gated.wait_calls == 0:
        time.sleep(0.01)  # let the service thread reach the barrier
    assert not os.path.exists(ckpt.meta_path)
    gated.gate.set()
    assert svc.barrier(timeout=10)
    assert ckpt.read_meta()["epoch"] == 9
    assert gated.wait_calls == 1
    svc.close(timeout=10)


def test_restore_if_exists_ignores_partial_orbax_tmp(tiny_config, tmp_path):
    """A crash mid-save leaves only Orbax's tmp dir (the rename into the
    slot path is the commit point). Auto-resume must see 'no checkpoint',
    never a torn slot."""
    import os

    state = create_state(tiny_config, jax.random.PRNGKey(0))
    ckpt = Checkpointer(str(tmp_path))
    os.makedirs(
        os.path.join(ckpt.dir, "checkpoint.orbax-checkpoint-tmp-1234567890")
    )
    out, epoch, resumed = ckpt.restore_if_exists(state)
    assert not resumed and epoch == 0 and out is state


def test_partial_restore_grafts_matching_leaves(tiny_config, tmp_path):
    """partial=True (reference expect_partial, main.py:165-169): after an
    architecture tweak, matching leaves restore and mismatched ones keep
    their fresh init instead of the whole restore failing."""
    import dataclasses

    import pytest

    cfg = tiny_config
    state = create_state(cfg, jax.random.PRNGKey(0))
    ckpt = Checkpointer(str(tmp_path))
    ckpt.save(state, epoch=3)

    # Same generators, wider discriminators: disc shapes no longer match.
    cfg2 = dataclasses.replace(
        cfg,
        model=dataclasses.replace(
            cfg.model,
            discriminator=dataclasses.replace(
                cfg.model.discriminator,
                filters=cfg.model.discriminator.filters * 2,
            ),
        ),
    )
    template = create_state(cfg2, jax.random.PRNGKey(9))

    # Strict restore must fail on the shape mismatch...
    with pytest.raises(Exception):
        ckpt.restore(template)

    # ...partial restore grafts generators + epoch, keeps fresh discs.
    restored, next_epoch = ckpt.restore(template, partial=True)
    assert next_epoch == 4
    assert _tree_equal(restored.g_params, state.g_params)
    assert _tree_equal(restored.f_params, state.f_params)
    assert _tree_equal(restored.dx_params, template.dx_params)
    assert not _tree_equal(restored.dx_params, state.dx_params)

    # With a fully matching template, partial == strict.
    same = create_state(cfg, jax.random.PRNGKey(9))
    full, _ = ckpt.restore(same, partial=True)
    assert _tree_equal(full, state)


def test_partial_restore_rejects_total_param_mismatch(tiny_config, tmp_path):
    """When no parameter array matches (every net resized), only shape-()
    counters could graft — that's a wrong checkpoint, not a resume: raise
    instead of silently 'resuming' with untrained networks at a late epoch."""
    import dataclasses

    import pytest

    cfg = tiny_config
    ckpt = Checkpointer(str(tmp_path))
    ckpt.save(create_state(cfg, jax.random.PRNGKey(0)), epoch=0)

    g = cfg.model.generator
    cfg2 = dataclasses.replace(
        cfg,
        model=dataclasses.replace(
            cfg.model,
            generator=dataclasses.replace(g, filters=g.filters * 2),
            discriminator=dataclasses.replace(
                cfg.model.discriminator,
                filters=cfg.model.discriminator.filters * 2,
            ),
        ),
    )
    template = create_state(cfg2, jax.random.PRNGKey(1))
    with pytest.raises(ValueError, match="wrong checkpoint"):
        ckpt.restore(template, partial=True)


def test_checkpoint_meta_records_architecture(tmp_path):
    """Self-describing slots: save() records the model architecture and
    Config.model_from_meta rebuilds it — the translate.py contract."""
    from cyclegan_tpu.config import (
        Config,
        DiscriminatorConfig,
        GeneratorConfig,
        ModelConfig,
    )
    from cyclegan_tpu.train import create_state
    from cyclegan_tpu.utils.checkpoint import Checkpointer

    cfg = Config(
        model=ModelConfig(
            generator=GeneratorConfig(filters=8, num_residual_blocks=3),
            discriminator=DiscriminatorConfig(filters=8),
            image_size=32,
            scan_blocks=True,
        )
    )
    state = create_state(cfg, jax.random.PRNGKey(0))
    ckpt = Checkpointer(str(tmp_path))
    ckpt.save(state, epoch=4, meta=cfg.model_meta())

    meta = Checkpointer(str(tmp_path)).read_meta()
    assert meta["epoch"] == 4
    rebuilt = Config.model_from_meta(meta)
    assert rebuilt == cfg.model

    # Overrides win; unknown future keys are tolerated.
    assert Config.model_from_meta(meta, image_size=64).image_size == 64
    meta["model"]["from_the_future"] = 1
    meta["model"]["generator"]["also_new"] = 2
    assert Config.model_from_meta(meta) == cfg.model


def test_model_from_meta_tolerates_legacy_sidecar():
    """Pre-r2 sidecars only carry {'epoch': N}: defaults must come back."""
    from cyclegan_tpu.config import Config, ModelConfig

    assert Config.model_from_meta({"epoch": 3}) == ModelConfig()
    assert Config.model_from_meta({}) == ModelConfig()
    assert Config.model_from_meta({}, scan_blocks=True).scan_blocks


def test_model_from_cli_and_meta_field_precedence():
    """Each explicitly-passed flag overrides ONLY its own field; unset
    flags defer to recorded values (the translate/evaluate/convert CLI
    contract)."""
    from cyclegan_tpu.config import (
        Config,
        DiscriminatorConfig,
        GeneratorConfig,
        ModelConfig,
    )

    recorded = Config(
        model=ModelConfig(
            generator=GeneratorConfig(filters=32, num_residual_blocks=6),
            discriminator=DiscriminatorConfig(filters=32),
            image_size=128,
            scan_blocks=True,
        )
    ).model_meta()

    # No flags: everything recorded comes back.
    got = Config.model_from_cli_and_meta(recorded)
    assert got.generator.filters == 32 and got.scan_blocks is True

    # One flag: the OTHER recorded fields must survive (a blanket
    # override to class defaults here once broke orbax restore).
    got = Config.model_from_cli_and_meta(recorded, residual_blocks=4)
    assert got.generator.num_residual_blocks == 4
    assert got.generator.filters == 32  # NOT reset to 64
    assert got.discriminator.filters == 32
    assert got.image_size == 128

    got = Config.model_from_cli_and_meta(recorded, filters=8)
    assert got.generator.filters == 8 and got.discriminator.filters == 8
    assert got.generator.num_residual_blocks == 6  # NOT reset to 9


# -- checkpoint ring (keep > 1): slot naming, pruning, verify, fallback ----


def _np_state(tag: float):
    return {"w": np.full((8,), tag, np.float32),
            "b": np.arange(4, dtype=np.float32) * tag}


def _np_template():
    return {"w": np.zeros((8,), np.float32),
            "b": np.zeros((4,), np.float32)}


def _tamper_one_array_file(slot):
    """Flip bytes in one payload file inside a committed slot."""
    import os

    for root, _, files in os.walk(slot):
        for name in files:
            if name.endswith((".json", ".txt")) or "manifest" in name:
                continue
            p = os.path.join(root, name)
            if os.path.getsize(p) > 0:
                with open(p, "r+b") as f:
                    data = f.read()
                    f.seek(0)
                    f.write(bytes(b ^ 0xFF for b in data[:64]) + data[64:])
                return p
    raise AssertionError(f"no payload file to tamper in {slot}")


def test_ring_keeps_k_slots_prunes_oldest(tmp_path):
    import os

    ckpt = Checkpointer(str(tmp_path), keep=3)
    for e in range(5):
        ckpt.save(_np_state(float(e)), epoch=e)
    assert [e for e, _ in ckpt.slots()] == [4, 3, 2]  # newest first
    names = sorted(os.listdir(ckpt.dir))
    assert "checkpoint-e00004" in names
    assert "checkpoint-e00000" not in names  # pruned with its manifest
    assert not [n for n in names if "e00000" in n or "e00001" in n]
    restored, next_epoch = ckpt.restore(_np_template())
    assert next_epoch == 5
    assert np.array_equal(np.asarray(restored["w"]), _np_state(4.0)["w"])


def test_legacy_keep1_slot_name_unchanged(tiny_config, tmp_path):
    """keep=1 must stay byte-compatible with every pre-ring run: the
    single slot is still named `checkpoint` (no epoch suffix)."""
    import os

    state = create_state(tiny_config, jax.random.PRNGKey(0))
    ckpt = Checkpointer(str(tmp_path))  # keep defaults to 1
    ckpt.save(state, epoch=7)
    assert os.path.isdir(os.path.join(ckpt.dir, "checkpoint"))
    assert not [n for n in os.listdir(ckpt.dir)
                if n.startswith("checkpoint-e")]


def test_ring_verify_detects_tampering_and_restore_falls_back(tmp_path):
    """The acceptance path for a corrupted newest slot: verify() fails
    on the sha256 manifest, restore() names it and falls back to the
    newest slot that still verifies, rewinding the resume epoch."""
    class Rec:
        def __init__(self):
            self.events = []

        def event(self, kind, /, **f):
            self.events.append(dict(f, event=kind))

    rec = Rec()
    ckpt = Checkpointer(str(tmp_path), keep=2, telemetry=rec)
    ckpt.save(_np_state(1.0), epoch=1)
    ckpt.save(_np_state(2.0), epoch=2)
    (_, newest), (_, older) = ckpt.slots()[0], ckpt.slots()[1]
    assert ckpt.verify(newest)[0] and ckpt.verify(older)[0]

    _tamper_one_array_file(newest)
    ok, detail = ckpt.verify(newest)
    assert not ok and "sha256" in detail

    restored, next_epoch = ckpt.restore(_np_template())
    assert next_epoch == 2  # slot e1: rewound past the corrupt e2
    assert np.array_equal(np.asarray(restored["w"]), _np_state(1.0)["w"])
    (ev,) = [e for e in rec.events if e["event"] == "ckpt_fallback"]
    assert ev["slot"] == "checkpoint-e00001"
    assert any("checkpoint-e00002" in f for f in ev["failed"])


def test_ring_every_slot_corrupt_raises_naming_slots(tmp_path):
    import pytest

    ckpt = Checkpointer(str(tmp_path), keep=2)
    ckpt.save(_np_state(1.0), epoch=1)
    ckpt.save(_np_state(2.0), epoch=2)
    for _, slot in ckpt.slots():
        _tamper_one_array_file(slot)
    with pytest.raises(RuntimeError, match="failed verification") as e:
        ckpt.restore(_np_template())
    assert "checkpoint-e00001" in str(e.value)
    assert "checkpoint-e00002" in str(e.value)


def test_restore_for_cli_corrupt_ring_exits_with_guidance(tmp_path):
    import pytest

    ckpt = Checkpointer(str(tmp_path), keep=1)
    ckpt.save(_np_state(3.0), epoch=0)
    _tamper_one_array_file(ckpt.slot)
    with pytest.raises(SystemExit) as e:
        ckpt.restore_for_cli(_np_template())
    msg = str(e.value)
    assert "checkpoint restore failed" in msg
    assert "sha256" in msg  # the corruption guidance, not just orbax noise


def test_slot_without_manifest_is_accepted_unverified(tmp_path):
    """A crash between Orbax's commit rename and the manifest write
    leaves a complete slot with no manifest: restore must accept it
    (the rename IS the commit point) rather than strand the run."""
    import os

    ckpt = Checkpointer(str(tmp_path), keep=2)
    ckpt.save(_np_state(5.0), epoch=5)
    manifest = [os.path.join(ckpt.dir, n) for n in os.listdir(ckpt.dir)
                if "manifest" in n]
    for m in manifest:
        os.remove(m)
    ok, detail = ckpt.verify()
    assert ok and "unverified" in detail
    restored, next_epoch = ckpt.restore(_np_template())
    assert next_epoch == 6
    assert np.array_equal(np.asarray(restored["w"]), _np_state(5.0)["w"])


def test_save_with_injected_io_error_retries_and_verifies(tmp_path):
    """--inject ckpt_io_error@epoch=N: the save's first attempt raises
    inside the retry wrapper, the bounded backoff absorbs it (a `retry`
    event lands in the stream), and the committed slot verifies."""
    from cyclegan_tpu.resil import FaultInjector

    class Rec:
        def __init__(self):
            self.events = []

        def event(self, kind, /, **f):
            self.events.append(dict(f, event=kind))

    rec = Rec()
    inj = FaultInjector.from_spec("ckpt_io_error@epoch=2", telemetry=rec)
    ckpt = Checkpointer(str(tmp_path), keep=2, telemetry=rec, injector=inj)
    ckpt.save(_np_state(2.0), epoch=2)
    retries = [e for e in rec.events
               if e["event"] == "retry" and e["site"] == "ckpt"]
    assert len(retries) == 1 and retries[0]["attempt"] == 1
    assert inj.pending() == []
    assert ckpt.verify()[0]
    restored, next_epoch = ckpt.restore(_np_template())
    assert next_epoch == 3
    assert np.array_equal(np.asarray(restored["w"]), _np_state(2.0)["w"])


def test_restored_state_survives_donation_roundtrip(tiny_config, tmp_path):
    """Restored arrays must be XLA-owned buffers. The train step DONATES
    its state argument; before restore() rebuffered its output, donating
    an orbax/tensorstore-backed array let XLA scribble on memory it did
    not own — resumed runs wrote NaN-riddled checkpoints and
    intermittently died with glibc heap-corruption aborts. Pin the safe
    path: restore, donate every leaf through a jitted step, save the
    result, and roundtrip it exactly."""
    state = create_state(tiny_config, jax.random.PRNGKey(0))
    ckpt = Checkpointer(str(tmp_path), keep=2)
    ckpt.save(state, epoch=0)
    template = create_state(tiny_config, jax.random.PRNGKey(1))
    restored, _ = ckpt.restore(template)

    donate = jax.jit(lambda s: jax.tree.map(lambda x: x + 0, s),
                     donate_argnums=0)
    out = donate(restored)
    jax.block_until_ready(out)
    ckpt.save(out, epoch=1)
    back, next_epoch = ckpt.restore(template)
    assert next_epoch == 2
    assert _tree_equal(back, out)
