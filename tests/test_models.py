"""Model-zoo tests (SURVEY.md §4: output shapes 256^2 -> 256^2x3 and
256^2 -> 32x32x1 patch map; param counts ~11.4M / ~2.77M)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cyclegan_tpu.config import DiscriminatorConfig, GeneratorConfig
from cyclegan_tpu.models import PatchGANDiscriminator, ResNetGenerator


def n_params(tree):
    return sum(int(np.prod(l.shape)) for l in jax.tree.leaves(tree))


@pytest.fixture(scope="module")
def full_gen():
    gen = ResNetGenerator()
    x = jnp.zeros((1, 256, 256, 3))
    params = jax.eval_shape(gen.init, jax.random.PRNGKey(0), x)
    return gen, params


def test_generator_param_count(full_gen):
    _, params = full_gen
    # Reference gen_G has ~11.4M params (SURVEY.md §2.1, model.py:129-169).
    assert n_params(params) == 11_383_427


def test_discriminator_param_count():
    disc = PatchGANDiscriminator()
    x = jnp.zeros((1, 256, 256, 3))
    params = jax.eval_shape(disc.init, jax.random.PRNGKey(0), x)
    assert n_params(params) == 2_765_633


def test_generator_output_shape_256(full_gen):
    gen, params = full_gen
    x = jnp.zeros((2, 256, 256, 3))
    out = jax.eval_shape(gen.apply, params, x)
    assert out.shape == (2, 256, 256, 3)


def test_discriminator_patch_map_shape():
    disc = PatchGANDiscriminator()
    x = jnp.zeros((2, 256, 256, 3))
    params = jax.eval_shape(disc.init, jax.random.PRNGKey(0), x)
    out = jax.eval_shape(disc.apply, params, x)
    assert out.shape == (2, 32, 32, 1)  # 70x70 PatchGAN logits map


def test_generator_output_shape_512(full_gen):
    # Fully convolutional: 512^2 config (BASELINE.md) reuses the same params.
    gen, params = full_gen
    x = jnp.zeros((1, 512, 512, 3))
    out = jax.eval_shape(gen.apply, params, x)
    assert out.shape == (1, 512, 512, 3)


def test_generator_tanh_range():
    gen = ResNetGenerator(config=GeneratorConfig(filters=4, num_residual_blocks=1))
    x = jax.random.normal(jax.random.PRNGKey(1), (1, 32, 32, 3))
    params = gen.init(jax.random.PRNGKey(0), x)
    y = gen.apply(params, x)
    assert float(jnp.max(jnp.abs(y))) <= 1.0


def test_discriminator_logits_unbounded_sign():
    # Raw logits head: no activation (model.py:207-211)
    disc = PatchGANDiscriminator(config=DiscriminatorConfig(filters=4))
    x = jax.random.normal(jax.random.PRNGKey(1), (1, 32, 32, 3)) * 3
    params = disc.init(jax.random.PRNGKey(0), x)
    y = np.asarray(disc.apply(params, x))
    assert y.min() < 0 or y.max() > 0  # not squashed


def test_bfloat16_compute_fp32_params():
    gen = ResNetGenerator(
        config=GeneratorConfig(filters=4, num_residual_blocks=1), dtype=jnp.bfloat16
    )
    x = jax.random.normal(jax.random.PRNGKey(1), (1, 32, 32, 3))
    params = gen.init(jax.random.PRNGKey(0), x)
    for leaf in jax.tree.leaves(params):
        assert leaf.dtype == jnp.float32
    y = gen.apply(params, x)
    assert y.dtype == x.dtype  # cast back at the boundary
    assert np.isfinite(np.asarray(y)).all()


def test_init_statistics_match_reference():
    # Conv kernels and IN gammas ~ N(0, 0.02); biases/betas zero
    # (reference model.py:10-11).
    gen = ResNetGenerator()
    x = jnp.zeros((1, 64, 64, 3))
    params = gen.init(jax.random.PRNGKey(0), x)
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    kernel_stds, zeros_ok = [], True
    for path, leaf in flat:
        name = jax.tree_util.keystr(path)
        arr = np.asarray(leaf)
        if "kernel" in name or "scale" in name:
            kernel_stds.append(arr.std())
        elif "bias" in name:
            zeros_ok &= (arr == 0).all()
    assert zeros_ok
    assert 0.015 < np.mean(kernel_stds) < 0.025


def test_remat_is_semantically_identical():
    """remat=True (jax.checkpoint around residual blocks, the 512^2 HBM
    relief) must not change values or gradients — only the memory/compute
    trade."""
    cfg = GeneratorConfig(filters=4, num_residual_blocks=2)
    x = jnp.asarray(np.random.RandomState(0).rand(2, 16, 16, 3), jnp.float32)
    plain = ResNetGenerator(config=cfg, remat=False)
    ckpt = ResNetGenerator(config=cfg, remat=True)
    params = plain.init(jax.random.PRNGKey(0), x)

    np.testing.assert_array_equal(
        np.asarray(plain.apply(params, x)), np.asarray(ckpt.apply(params, x))
    )

    def loss(m, p):
        return jnp.sum(m.apply(p, x) ** 2)

    g_plain = jax.grad(lambda p: loss(plain, p))(params)
    g_ckpt = jax.grad(lambda p: loss(ckpt, p))(params)
    for a, b in zip(jax.tree.leaves(g_plain), jax.tree.leaves(g_ckpt)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7)
