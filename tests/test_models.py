"""Model-zoo tests (SURVEY.md §4: output shapes 256^2 -> 256^2x3 and
256^2 -> 32x32x1 patch map; param counts ~11.4M / ~2.77M)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cyclegan_tpu.config import DiscriminatorConfig, GeneratorConfig
from cyclegan_tpu.models import PatchGANDiscriminator, ResNetGenerator


def n_params(tree):
    return sum(int(np.prod(l.shape)) for l in jax.tree.leaves(tree))


@pytest.fixture(scope="module")
def full_gen():
    gen = ResNetGenerator()
    x = jnp.zeros((1, 256, 256, 3))
    params = jax.eval_shape(gen.init, jax.random.PRNGKey(0), x)
    return gen, params


def test_generator_param_count(full_gen):
    _, params = full_gen
    # Reference gen_G has ~11.4M params (SURVEY.md §2.1, model.py:129-169).
    assert n_params(params) == 11_383_427


def test_discriminator_param_count():
    disc = PatchGANDiscriminator()
    x = jnp.zeros((1, 256, 256, 3))
    params = jax.eval_shape(disc.init, jax.random.PRNGKey(0), x)
    assert n_params(params) == 2_765_633


def test_generator_output_shape_256(full_gen):
    gen, params = full_gen
    x = jnp.zeros((2, 256, 256, 3))
    out = jax.eval_shape(gen.apply, params, x)
    assert out.shape == (2, 256, 256, 3)


def test_discriminator_patch_map_shape():
    disc = PatchGANDiscriminator()
    x = jnp.zeros((2, 256, 256, 3))
    params = jax.eval_shape(disc.init, jax.random.PRNGKey(0), x)
    out = jax.eval_shape(disc.apply, params, x)
    assert out.shape == (2, 32, 32, 1)  # 70x70 PatchGAN logits map


def test_generator_output_shape_512(full_gen):
    # Fully convolutional: 512^2 config (BASELINE.md) reuses the same params.
    gen, params = full_gen
    x = jnp.zeros((1, 512, 512, 3))
    out = jax.eval_shape(gen.apply, params, x)
    assert out.shape == (1, 512, 512, 3)


def test_generator_tanh_range():
    gen = ResNetGenerator(config=GeneratorConfig(filters=4, num_residual_blocks=1))
    x = jax.random.normal(jax.random.PRNGKey(1), (1, 32, 32, 3))
    params = gen.init(jax.random.PRNGKey(0), x)
    y = gen.apply(params, x)
    assert float(jnp.max(jnp.abs(y))) <= 1.0


def test_discriminator_logits_unbounded_sign():
    # Raw logits head: no activation (model.py:207-211)
    disc = PatchGANDiscriminator(config=DiscriminatorConfig(filters=4))
    x = jax.random.normal(jax.random.PRNGKey(1), (1, 32, 32, 3)) * 3
    params = disc.init(jax.random.PRNGKey(0), x)
    y = np.asarray(disc.apply(params, x))
    assert y.min() < 0 or y.max() > 0  # not squashed


def test_bfloat16_compute_fp32_params():
    gen = ResNetGenerator(
        config=GeneratorConfig(filters=4, num_residual_blocks=1), dtype=jnp.bfloat16
    )
    x = jax.random.normal(jax.random.PRNGKey(1), (1, 32, 32, 3))
    params = gen.init(jax.random.PRNGKey(0), x)
    for leaf in jax.tree.leaves(params):
        assert leaf.dtype == jnp.float32
    y = gen.apply(params, x)
    assert y.dtype == x.dtype  # cast back at the boundary
    assert np.isfinite(np.asarray(y)).all()


def test_init_statistics_match_reference():
    # Conv kernels and IN gammas ~ N(0, 0.02); biases/betas zero
    # (reference model.py:10-11).
    gen = ResNetGenerator()
    x = jnp.zeros((1, 64, 64, 3))
    params = gen.init(jax.random.PRNGKey(0), x)
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    kernel_stds, zeros_ok = [], True
    for path, leaf in flat:
        name = jax.tree_util.keystr(path)
        arr = np.asarray(leaf)
        if "kernel" in name or "scale" in name:
            kernel_stds.append(arr.std())
        elif "bias" in name:
            zeros_ok &= (arr == 0).all()
    assert zeros_ok
    assert 0.015 < np.mean(kernel_stds) < 0.025


def test_remat_is_semantically_identical():
    """remat=True (jax.checkpoint around residual blocks, the 512^2 HBM
    relief) must not change values or gradients — only the memory/compute
    trade."""
    cfg = GeneratorConfig(filters=4, num_residual_blocks=2)
    x = jnp.asarray(np.random.RandomState(0).rand(2, 16, 16, 3), jnp.float32)
    plain = ResNetGenerator(config=cfg, remat=False)
    ckpt = ResNetGenerator(config=cfg, remat=True)
    params = plain.init(jax.random.PRNGKey(0), x)

    np.testing.assert_array_equal(
        np.asarray(plain.apply(params, x)), np.asarray(ckpt.apply(params, x))
    )

    def loss(m, p):
        return jnp.sum(m.apply(p, x) ** 2)

    g_plain = jax.grad(lambda p: loss(plain, p))(params)
    g_ckpt = jax.grad(lambda p: loss(ckpt, p))(params)
    for a, b in zip(jax.tree.leaves(g_plain), jax.tree.leaves(g_ckpt)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7)


class TestPadMode:
    """pad_mode="zero" (ModelConfig.pad_mode): conv built-in SAME padding
    instead of reflect-pad+VALID — a TPU perf option. The contract: the
    parameter TREE is identical across modes (checkpoints interchange),
    shapes are unchanged, but border numerics differ."""

    def _shapes(self, tree):
        return jax.tree.map(lambda l: l.shape, tree)

    def test_param_tree_identical_across_modes(self):
        x = jnp.zeros((1, 64, 64, 3))
        trees = {}
        for mode in ("reflect", "zero"):
            gen = ResNetGenerator(pad_mode=mode)
            trees[mode] = jax.eval_shape(gen.init, jax.random.PRNGKey(0), x)
        assert self._shapes(trees["reflect"]) == self._shapes(trees["zero"])

    def test_param_tree_identical_with_scan_blocks(self):
        x = jnp.zeros((1, 64, 64, 3))
        trees = {}
        for mode in ("reflect", "zero"):
            gen = ResNetGenerator(pad_mode=mode, scan_blocks=True)
            trees[mode] = jax.eval_shape(gen.init, jax.random.PRNGKey(0), x)
        assert self._shapes(trees["reflect"]) == self._shapes(trees["zero"])

    def test_zero_mode_shapes_and_border_numerics(self):
        from jax.tree_util import tree_map_with_path

        cfg = GeneratorConfig(filters=8, num_residual_blocks=2)
        x = jax.random.uniform(jax.random.PRNGKey(1), (1, 32, 32, 3),
                               minval=-1.0, maxval=1.0)

        def boost_norm_scales(params):
            # The reference-quirk IN gamma ~ N(0, 0.02) attenuates a
            # freshly-initialized net toward 0, which would hide the
            # border difference below any tolerance — set scales to 1.
            return tree_map_with_path(
                lambda path, l: (jnp.ones_like(l)
                                 if any(getattr(p, "key", None) == "scale"
                                        for p in path) else l),
                params)

        outs = {}
        for mode in ("reflect", "zero"):
            gen = ResNetGenerator(config=cfg, pad_mode=mode)
            params = gen.init(jax.random.PRNGKey(0), x)  # same seed, same tree
            outs[mode] = gen.apply(boost_norm_scales(params), x)
        assert outs["zero"].shape == outs["reflect"].shape == (1, 32, 32, 3)
        # same params, different border semantics -> outputs must differ
        # (if they matched, "zero" silently fell back to reflect)
        assert not np.allclose(np.asarray(outs["reflect"]),
                               np.asarray(outs["zero"]), atol=1e-5)

    def test_interior_agrees_for_identity_like_single_conv(self):
        # For a single 3x3 conv, padding only affects the 1-pixel border:
        # interiors must agree exactly between SAME and reflect+VALID.
        from cyclegan_tpu.ops.padding import reflect_pad
        import flax.linen as nn

        x = jax.random.uniform(jax.random.PRNGKey(2), (1, 16, 16, 4))
        conv = nn.Conv(4, (3, 3), padding="SAME", use_bias=False)
        params = conv.init(jax.random.PRNGKey(3), x)
        same = conv.apply(params, x)
        valid = nn.Conv(4, (3, 3), padding="VALID", use_bias=False).apply(
            params, reflect_pad(x, 1))
        np.testing.assert_allclose(np.asarray(same)[:, 1:-1, 1:-1, :],
                                   np.asarray(valid)[:, 1:-1, 1:-1, :],
                                   rtol=1e-5, atol=1e-6)


class TestPadImpl:
    """pad_impl="fused" (ModelConfig.pad_impl): reflect semantics
    scheduled as ReflectConv (zero-pad conv + fusible border corrections)
    instead of materialized reflect-pads. Contract: the param tree —
    paths AND shapes — is identical to pad_impl="pad" (checkpoints
    interchange), and same-params outputs agree to fp tolerance (unlike
    pad_mode="zero", which changes border semantics)."""

    def test_param_tree_identical_and_outputs_match(self):
        cfg = GeneratorConfig(filters=8, num_residual_blocks=2)
        x = jax.random.uniform(jax.random.PRNGKey(1), (1, 32, 32, 3),
                               minval=-1.0, maxval=1.0)
        gens = {impl: ResNetGenerator(config=cfg, pad_impl=impl)
                for impl in ("pad", "fused")}
        trees = {impl: jax.eval_shape(g.init, jax.random.PRNGKey(0), x)
                 for impl, g in gens.items()}
        assert (jax.tree.map(lambda l: (l.shape, l.dtype), trees["pad"]) ==
                jax.tree.map(lambda l: (l.shape, l.dtype), trees["fused"]))

        params = gens["pad"].init(jax.random.PRNGKey(0), x)
        out_pad = gens["pad"].apply(params, x)
        out_fused = gens["fused"].apply(params, x)  # same tree loads
        np.testing.assert_allclose(np.asarray(out_pad),
                                   np.asarray(out_fused),
                                   rtol=1e-4, atol=1e-5)

    def test_param_tree_identical_with_scan_blocks(self):
        x = jnp.zeros((1, 64, 64, 3))
        trees = {}
        for impl in ("pad", "fused"):
            gen = ResNetGenerator(pad_impl=impl, scan_blocks=True)
            trees[impl] = jax.eval_shape(gen.init, jax.random.PRNGKey(0), x)
        assert (jax.tree.map(lambda l: l.shape, trees["pad"]) ==
                jax.tree.map(lambda l: l.shape, trees["fused"]))

    def test_epilogue_param_tree_identical_and_outputs_match(self):
        # pad_impl="epilogue" re-schedules ResBlock IN->ReLU->reflect-pad
        # into the Pallas epilogue kernel (interpret mode on CPU). Same
        # contract as "fused": checkpoint-interchangeable tree, same-
        # params outputs agree to fp tolerance with the reference "pad"
        # schedule.
        cfg = GeneratorConfig(filters=8, num_residual_blocks=2)
        x = jax.random.uniform(jax.random.PRNGKey(1), (1, 32, 32, 3),
                               minval=-1.0, maxval=1.0)
        gens = {impl: ResNetGenerator(config=cfg, pad_impl=impl)
                for impl in ("pad", "epilogue")}
        trees = {impl: jax.eval_shape(g.init, jax.random.PRNGKey(0), x)
                 for impl, g in gens.items()}
        assert (jax.tree.map(lambda l: (l.shape, l.dtype), trees["pad"]) ==
                jax.tree.map(lambda l: (l.shape, l.dtype),
                             trees["epilogue"]))

        params = gens["pad"].init(jax.random.PRNGKey(0), x)
        out_pad = gens["pad"].apply(params, x)
        out_epi = gens["epilogue"].apply(params, x)  # same tree loads
        np.testing.assert_allclose(np.asarray(out_pad),
                                   np.asarray(out_epi),
                                   rtol=1e-4, atol=1e-5)

    def test_epilogue_param_tree_identical_with_scan_blocks(self):
        x = jnp.zeros((1, 64, 64, 3))
        trees = {}
        for impl in ("pad", "epilogue"):
            gen = ResNetGenerator(pad_impl=impl, scan_blocks=True)
            trees[impl] = jax.eval_shape(gen.init, jax.random.PRNGKey(0), x)
        assert (jax.tree.map(lambda l: l.shape, trees["pad"]) ==
                jax.tree.map(lambda l: l.shape, trees["epilogue"]))

    def test_epilogue_grad_matches_pad_schedule(self):
        # the Pallas custom_vjp (IN backward + pad-transpose) must
        # produce the same parameter gradients as the XLA composition.
        cfg = GeneratorConfig(filters=8, num_residual_blocks=1)
        x = jax.random.uniform(jax.random.PRNGKey(1), (1, 32, 32, 3),
                               minval=-1.0, maxval=1.0)
        gens = {impl: ResNetGenerator(config=cfg, pad_impl=impl)
                for impl in ("pad", "epilogue")}
        params = gens["pad"].init(jax.random.PRNGKey(0), x)
        grads = {}
        for impl, gen in gens.items():
            grads[impl] = jax.grad(
                lambda p: jnp.sum(gen.apply(p, x) ** 2))(params)
        flat_pad = jax.tree_util.tree_leaves(grads["pad"])
        flat_epi = jax.tree_util.tree_leaves(grads["epilogue"])
        for a, b in zip(flat_pad, flat_epi):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-3, atol=1e-4)

    def test_fused_init_statistics_match_conv_init(self):
        # ReflectConv must init kernels N(0, 0.02) like nn.Conv does
        # (reference model.py:10-11) — same init fn, same param dtype.
        cfg = GeneratorConfig(filters=32, num_residual_blocks=2)
        gen = ResNetGenerator(config=cfg, pad_impl="fused")
        params = gen.init(jax.random.PRNGKey(0),
                          jnp.zeros((1, 32, 32, 3)))
        kernels = [l for p, l in jax.tree_util.tree_flatten_with_path(params)[0]
                   if any(getattr(q, "key", None) == "kernel" for q in p)]
        flat = np.concatenate([np.asarray(k).ravel() for k in kernels])
        assert abs(flat.mean()) < 5e-3
        assert abs(flat.std() - 0.02) < 5e-3
        assert all(k.dtype == jnp.float32 for k in kernels)
