"""Fleet serving layer (cyclegan_tpu/serve/fleet): admission control,
EDF dispatch order, class-ordered load shedding, backpressure bounds,
continuous-batching refill, the HTTP 429 path, and the int8 tier.

The queueing/dispatch tests run against a fake engine (deterministic,
no compiles) so they probe the fleet's control plane, not XLA. The int8
tests use the real tiny engine at 16 px so both program tiers compile
in seconds on the CPU mesh.
"""

import os
import sys
import threading
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))

from cyclegan_tpu.config import GeneratorConfig, ModelConfig  # noqa: E402
from cyclegan_tpu.serve.fleet import (  # noqa: E402
    AdmissionController,
    DEFAULT_CLASSES,
    DeadlineClass,
    DeadlineExceeded,
    FleetConfig,
    FleetExecutor,
    ReplicaCrashed,
    ShedError,
    TenantSpec,
    class_map,
)
from cyclegan_tpu.serve.fleet.admission import FleetRequest  # noqa: E402

CLASSES = class_map(DEFAULT_CLASSES)
INTERACTIVE, BATCH, BEST_EFFORT = (CLASSES["interactive"],
                                   CLASSES["batch"],
                                   CLASSES["best_effort"])


def _req(klass, size=32, tier="base", now=None):
    return FleetRequest(np.zeros((size, size, 3), np.float32),
                        size, tier, klass, now=now)


class FakeEngine:
    """Engine-shaped test double: same routing surface the fleet uses
    (programs / buckets / tiers / run), with controllable flush latency
    and an optional gate that stalls flushes until released."""

    def __init__(self, sizes=(32,), buckets=(1, 4), tiers=("base",),
                 flush_s=0.0):
        self.programs = {(s, b): object()
                         for s in sizes for b in buckets}
        self._sizes = tuple(sorted(sizes))
        self._buckets = tuple(sorted(buckets))
        self._tiers = tuple(tiers)
        self.flush_s = flush_s
        self.gate = None  # threading.Event: run() waits on it when set
        self.entered = threading.Event()  # set each time run() starts
        self.flushes = []  # (n, size, tier, class names) log
        self._lock = threading.Lock()

    @property
    def max_batch(self):
        return self._buckets[-1]

    @property
    def tiers(self):
        return self._tiers

    def resolve_tier(self, tier):
        if tier is None or tier == "base":
            return "base"
        if tier in self._tiers:
            return tier
        raise ValueError(f"unknown tier {tier!r}; have {self._tiers}")

    def batch_bucket(self, n):
        for b in self._buckets:
            if n <= b:
                return b
        return None

    def size_bucket(self, h, w):
        side = max(h, w)
        for s in self._sizes:
            if side <= s:
                return s
        return self._sizes[-1]

    def run(self, batch_np, size=None, tier=None):
        self.entered.set()
        if self.gate is not None:
            assert self.gate.wait(timeout=30)
        if self.flush_s:
            time.sleep(self.flush_s)
        with self._lock:
            self.flushes.append((len(batch_np), size, tier))
        return (batch_np.copy(),), len(batch_np)


# -- deadline classes ------------------------------------------------------

def test_default_classes_are_strictly_ordered():
    assert (INTERACTIVE.deadline_ms < BATCH.deadline_ms
            < BEST_EFFORT.deadline_ms)
    assert (INTERACTIVE.shed_rank < BATCH.shed_rank
            < BEST_EFFORT.shed_rank)
    with pytest.raises(ValueError):
        DeadlineClass("bad", deadline_ms=0, shed_rank=0)
    with pytest.raises(ValueError):
        DeadlineClass("bad", deadline_ms=100, shed_rank=-1)


# -- EDF ordering ----------------------------------------------------------

def test_admission_pops_in_edf_order():
    """A later-arriving interactive request overtakes every queued batch
    request: the pop order is absolute deadline, not arrival."""
    adm = AdmissionController(capacity=16)
    t0 = time.perf_counter()
    early_batch = _req(BATCH, now=t0)
    late_batch = _req(BATCH, now=t0 + 0.001)
    interactive = _req(INTERACTIVE, now=t0 + 0.002)  # arrives LAST
    for r in (early_batch, late_batch, interactive):
        adm.offer(r)
    batch = adm.next_batch(max_n=3, max_wait_s=0.0)
    assert batch == [interactive, early_batch, late_batch]


def test_edf_degrades_to_fifo_within_a_class():
    adm = AdmissionController(capacity=16)
    t0 = time.perf_counter()
    reqs = [_req(BATCH, now=t0 + i * 1e-4) for i in range(4)]
    for r in reversed(reqs):  # offer out of order
        adm.offer(r)
    assert adm.next_batch(max_n=4, max_wait_s=0.0) == reqs


def test_batches_stay_homogeneous_in_size_and_tier():
    """Non-matching entries are put back, not dropped: the next pop
    serves them."""
    adm = AdmissionController(capacity=16)
    t0 = time.perf_counter()
    a = _req(INTERACTIVE, size=32, now=t0)
    b = _req(INTERACTIVE, size=16, now=t0 + 1e-4)
    c = _req(INTERACTIVE, size=32, now=t0 + 2e-4)
    for r in (a, b, c):
        adm.offer(r)
    assert adm.next_batch(max_n=4, max_wait_s=0.0) == [a, c]
    assert adm.next_batch(max_n=4, max_wait_s=0.0) == [b]


def test_expired_sheddable_dropped_expired_interactive_served():
    """A best_effort request whose deadline passed while queued is
    dropped at pop time (DeadlineExceeded); an expired interactive
    request still serves — late beats never for a user-facing reply."""
    tight = DeadlineClass("tick", deadline_ms=1, shed_rank=2)
    tight_inter = DeadlineClass("itick", deadline_ms=1, shed_rank=0)
    adm = AdmissionController(capacity=16)
    doomed = _req(tight)
    kept = _req(tight_inter)
    adm.offer(doomed)
    adm.offer(kept)
    time.sleep(0.02)  # both deadlines pass while queued
    batch = adm.next_batch(max_n=4, max_wait_s=0.0)
    assert batch == [kept]
    with pytest.raises(DeadlineExceeded):
        doomed.future.result(timeout=1)
    assert adm.stats()["shed_reasons"] == {"expired": 1}


# -- class-ordered shedding + backpressure bounds --------------------------

def test_shedding_evicts_lowest_class_first():
    adm = AdmissionController(capacity=2)
    be = _req(BEST_EFFORT)
    ba = _req(BATCH)
    adm.offer(be)
    adm.offer(ba)
    # Queue full. Interactive arrival evicts best_effort (not batch).
    inter = _req(INTERACTIVE)
    fut = adm.offer(inter)
    with pytest.raises(ShedError) as ei:
        be.future.result(timeout=1)
    assert ei.value.reason == "evicted" and ei.value.klass == "best_effort"
    assert ei.value.retry_after_s >= 1.0
    assert not fut.done() and not ba.future.done()
    # Another interactive arrival now evicts batch (next rank up).
    adm.offer(_req(INTERACTIVE))
    with pytest.raises(ShedError) as ei:
        ba.future.result(timeout=1)
    assert ei.value.klass == "batch"


def test_shedding_rejects_when_no_lower_class_queued():
    """best_effort arriving at a queue full of equal-or-higher classes
    is itself rejected — ShedError raised AT THE CALLER (the 429 path),
    never an eviction of better work."""
    adm = AdmissionController(capacity=2)
    adm.offer(_req(INTERACTIVE))
    adm.offer(_req(BATCH))
    with pytest.raises(ShedError) as ei:
        adm.offer(_req(BEST_EFFORT))
    assert ei.value.reason == "rejected"
    assert ei.value.retry_after_s >= 1.0
    # Same-class arrival at a same-class-full queue also rejects
    # (no victim has a STRICTLY lower class).
    with pytest.raises(ShedError):
        adm.offer(_req(BATCH))
    stats = adm.stats()
    assert stats["depth"] == 2 and stats["max_depth"] <= adm.capacity
    assert stats["shed"] == {"best_effort": 1, "batch": 1}
    assert stats["shed_reasons"] == {"rejected": 2}


def test_admission_depth_never_exceeds_capacity():
    adm = AdmissionController(capacity=4)
    admitted, shed = 0, 0
    for _ in range(20):
        try:
            adm.offer(_req(BATCH))
            admitted += 1
        except ShedError:
            shed += 1
    assert admitted == 4 and shed == 16
    assert adm.stats()["max_depth"] == 4


# -- fleet executor end-to-end (fake engine) -------------------------------

def test_fleet_serves_interactive_before_earlier_batch():
    """With the single replica pinned busy, queued requests re-order by
    class: the interactive request submitted LAST is flushed first once
    the replica frees."""
    eng = FakeEngine()
    eng.gate = threading.Event()
    fleet = FleetExecutor(eng, FleetConfig(
        n_replicas=1, capacity=16, max_batch=1, max_wait_ms=0.0))
    img = np.zeros((32, 32, 3), np.float32)
    order, order_lock = [], threading.Lock()

    def tag(name, fut):
        def cb(_):
            with order_lock:
                order.append(name)
        fut.add_done_callback(cb)
        return fut

    pin = tag("pin", fleet.submit(img, klass="batch"))  # occupies the replica
    assert eng.entered.wait(timeout=10)
    futs_batch = [tag(f"batch{i}", fleet.submit(img, klass="batch"))
                  for i in range(2)]
    fut_inter = tag("interactive", fleet.submit(img, klass="interactive"))
    eng.gate.set()
    for f in [pin, fut_inter] + futs_batch:
        assert f.result(timeout=30)["fake"].shape == (32, 32, 3)
    summary = fleet.close()
    # The pin resolves first (it was already on the replica); the
    # interactive request — submitted last — overtakes both queued
    # batch requests.
    assert order == ["pin", "interactive", "batch0", "batch1"]
    assert summary["classes"]["interactive"]["deadline_misses"] == 0
    assert summary["shed"] == {}


def test_fleet_sheds_best_effort_before_interactive_misses():
    """The acceptance shape: saturate a tiny fleet with best_effort,
    sprinkle interactive on top — best_effort sheds (submit-time 429s
    and/or evictions) while interactive serves with zero deadline
    misses and nothing interactive shed."""
    eng = FakeEngine(flush_s=0.005)
    fleet = FleetExecutor(eng, FleetConfig(
        n_replicas=1, capacity=4, max_batch=4, max_wait_ms=1.0))
    img = np.zeros((32, 32, 3), np.float32)
    futs, rejected = [], 0
    for i in range(40):
        try:
            futs.append(fleet.submit(img, klass="best_effort"))
        except ShedError as e:
            assert e.klass == "best_effort"
            rejected += 1
        if i % 10 == 9:
            futs.append(fleet.submit(img, klass="interactive"))
    done = 0
    for f in futs:
        try:
            f.result(timeout=30)
            done += 1
        except (ShedError, DeadlineExceeded):
            pass
    summary = fleet.close()
    assert done >= 4  # the fleet still made progress under overload
    shed = summary["shed"]
    assert shed.get("best_effort", 0) + rejected > 0
    assert "interactive" not in shed
    assert summary["classes"]["interactive"]["deadline_misses"] == 0


def test_fleet_refills_partial_buckets_while_replica_busy():
    """Continuous batching: with one replica held down by a full slow
    flush, later arrivals go out to the second replica as a PARTIAL
    bucket at the wait-window edge, flagged ``refill``."""
    eng = FakeEngine(flush_s=0.15)
    fleet = FleetExecutor(eng, FleetConfig(
        n_replicas=2, capacity=64, max_batch=4, max_wait_ms=20.0))
    img = np.zeros((32, 32, 3), np.float32)
    full = [fleet.submit(img) for _ in range(4)]  # full flush, replica A
    assert eng.entered.wait(timeout=10)
    time.sleep(0.01)
    partial = [fleet.submit(img) for _ in range(2)]  # lands on replica B
    for f in full + partial:
        f.result(timeout=30)
    summary = fleet.close()
    assert summary["refill_flushes"] >= 1
    assert summary["n_images"] == 6
    fills = sorted(n for n, _, _ in eng.flushes)
    assert fills == [2, 4]


def test_fleet_config_and_submit_validation():
    eng = FakeEngine()
    with pytest.raises(ValueError, match="n_replicas"):
        FleetConfig(n_replicas=0)
    with pytest.raises(ValueError, match="default_class"):
        FleetConfig(default_class="platinum")
    with pytest.raises(ValueError, match="exceeds"):
        FleetExecutor(eng, FleetConfig(max_batch=64))
    # A class routed to a tier the engine never compiled fails at
    # construction, not per-request.
    with pytest.raises(ValueError, match="tier"):
        FleetExecutor(eng, FleetConfig(
            classes=(DeadlineClass("fast", 500, 0, tier="int8"),),
            default_class="fast"))
    fleet = FleetExecutor(eng, FleetConfig(n_replicas=1))
    img = np.zeros((32, 32, 3), np.float32)
    with pytest.raises(KeyError, match="platinum"):
        fleet.submit(img, klass="platinum")
    with pytest.raises(ValueError, match="tier"):
        fleet.submit(img, tier="int8")
    fleet.close()
    with pytest.raises(RuntimeError, match="closed"):
        fleet.submit(img)
    assert fleet.close() == {}  # idempotent


def test_fleet_stats_snapshot_shape():
    eng = FakeEngine()
    fleet = FleetExecutor(eng, FleetConfig(n_replicas=2))
    img = np.zeros((32, 32, 3), np.float32)
    for _ in range(3):
        fleet.submit(img).result(timeout=30)
    snap = fleet.stats()
    assert snap["n_replicas"] == 2
    assert snap["admission"]["capacity"] == 256
    assert snap["n_images_done"] == 3
    assert "batch" in snap["classes"]
    assert snap["tiers"] == ["base"]
    fleet.close()


# -- HTTP front-end: 429 + Retry-After -------------------------------------

def test_http_fleet_sheds_with_429_and_retry_after():
    import io
    import json
    import urllib.error
    import urllib.request

    from cyclegan_tpu.serve.server import make_server

    eng = FakeEngine()
    eng.gate = threading.Event()
    fleet = FleetExecutor(eng, FleetConfig(
        n_replicas=1, capacity=1, max_batch=1, max_wait_ms=0.0))
    server, app = make_server(fleet, port=0, fleet=True)
    host, port = server.server_address[:2]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        img = np.zeros((32, 32, 3), np.float32)
        # Pin the replica, then fill the 1-slot queue.
        pinned = fleet.submit(img, klass="best_effort")
        assert eng.entered.wait(timeout=10)
        queued = fleet.submit(img, klass="best_effort")

        buf = io.BytesIO()
        np.save(buf, np.zeros((32, 32, 3), np.uint8))
        req = urllib.request.Request(
            f"http://{host}:{port}/translate?class=best_effort",
            data=buf.getvalue(), method="POST")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=30)
        assert ei.value.code == 429
        assert int(ei.value.headers["Retry-After"]) >= 1
        body = json.loads(ei.value.read())
        assert body["error"] == "overloaded"
        assert body["class"] == "best_effort"
        assert body["retry_after_s"] >= 1.0

        eng.gate.set()
        pinned.result(timeout=30)
        queued.result(timeout=30)
        with urllib.request.urlopen(
                f"http://{host}:{port}/stats", timeout=30) as r:
            stats = json.loads(r.read())
        assert stats["fleet"] is True and stats["n_shed"] == 1
        assert stats["admission"]["shed"] == {"best_effort": 1}
    finally:
        server.shutdown()
        fleet.close()


# -- int8 tier (real engine) -----------------------------------------------

def _tiny_model_cfg():
    return ModelConfig(
        generator=GeneratorConfig(filters=4, num_residual_blocks=1),
        image_size=16,
        compute_dtype="float32",
    )


@pytest.fixture(scope="module")
def int8_engine():
    import jax
    import jax.numpy as jnp

    from cyclegan_tpu.serve.engine import (
        InferenceEngine,
        ServeConfig,
        build_generator,
    )

    cfg = _tiny_model_cfg()
    gen = build_generator(cfg)
    params = gen.init(jax.random.PRNGKey(0),
                      jnp.zeros((1, 16, 16, 3), jnp.float32))
    return InferenceEngine(
        cfg, params,
        serve_cfg=ServeConfig(batch_buckets=(2,), sizes=(16,),
                              dtype="float32", int8_tier=True))


def test_int8_quantize_roundtrip_error_is_small_but_nonzero():
    """Per-output-channel symmetric quantization: the dequantized weight
    differs from the original (it IS lossy) but by at most one quant
    step of that channel's scale."""
    from cyclegan_tpu.serve.engine import (
        dequantize_params,
        quantize_params_int8,
    )

    rng = np.random.RandomState(0)
    params = {"conv": {"kernel": rng.randn(3, 3, 4, 8)
                       .astype(np.float32)},
              "bias": rng.randn(8).astype(np.float32)}
    q = quantize_params_int8(params)
    leaf = q["conv"]["kernel"]
    assert set(leaf) == {"int8_q", "int8_scale"}
    assert np.asarray(leaf["int8_q"]).dtype == np.int8
    assert np.asarray(leaf["int8_scale"]).shape == (1, 1, 1, 8)
    # 1-D leaves (biases, norm params) stay full precision.
    assert np.asarray(q["bias"]).dtype == np.float32
    dq = dequantize_params(q)
    err = np.abs(np.asarray(dq["conv"]["kernel"])
                 - params["conv"]["kernel"])
    assert float(err.max()) > 0.0  # lossy, really quantized
    step = np.asarray(leaf["int8_scale"])
    assert np.all(err <= step * 0.5 + 1e-7)  # round-to-nearest bound
    np.testing.assert_array_equal(np.asarray(dq["bias"]),
                                  params["bias"])


def test_int8_tier_compiles_and_tracks_base(int8_engine):
    eng = int8_engine
    assert eng.tiers == ("base", "int8")
    assert set(eng.programs_int8) == set(eng.programs)
    assert eng.resolve_tier(None) == "base"
    assert eng.resolve_tier("base") == "base"
    assert eng.resolve_tier("int8") == "int8"
    with pytest.raises(ValueError):
        eng.resolve_tier("fp4")
    x = np.random.RandomState(1).uniform(
        -1, 1, (2, 16, 16, 3)).astype(np.float32)
    base = np.asarray(eng.run(x, size=16)[0][0])
    int8 = np.asarray(eng.run(x, size=16, tier="int8")[0][0])
    assert int8.dtype == np.float32  # f32 accumulate/output
    assert np.all(np.isfinite(int8))
    # Weight-only int8 over an instance-norm trunk: outputs stay close
    # to the f32 program (tanh-bounded, so absolute tolerance).
    assert float(np.max(np.abs(int8 - base))) < 0.05


def test_int8_tier_refuses_fused_cycle():
    from cyclegan_tpu.serve.engine import ServeConfig

    with pytest.raises(ValueError, match="int8"):
        ServeConfig(with_cycle=True, int8_tier=True)


def test_base_engine_rejects_int8_tier_requests(int8_engine):
    import jax
    import jax.numpy as jnp

    from cyclegan_tpu.serve.engine import (
        InferenceEngine,
        ServeConfig,
        build_generator,
    )

    cfg = _tiny_model_cfg()
    gen = build_generator(cfg)
    params = gen.init(jax.random.PRNGKey(0),
                      jnp.zeros((1, 16, 16, 3), jnp.float32))
    eng = InferenceEngine(cfg, params,
                          serve_cfg=ServeConfig(batch_buckets=(2,),
                                                sizes=(16,)))
    assert eng.tiers == ("base",)
    with pytest.raises(ValueError, match="int8"):
        eng.resolve_tier("int8")


# -- hot-path no-sync coverage ---------------------------------------------

def test_no_sync_check_covers_fleet_directory():
    from check_no_sync import hot_path_entries, run_check

    entries = dict(hot_path_entries())
    for mod in ("admission", "autoscale", "cascade", "classes",
                "controller", "replica", "__init__"):
        assert entries.get(f"cyclegan_tpu/serve/fleet/{mod}.py") is True
    assert run_check() == []


# -- self-healing (crash detection, re-enqueue, respawn, circuit) ----------

class _Recorder:
    """Thread-safe logger double (replica + monitor threads emit)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.events = []

    def event(self, kind, /, **fields):
        with self._lock:
            self.events.append(dict(fields, event=kind))

    def kinds(self):
        with self._lock:
            return [e["event"] for e in self.events]

    def of(self, kind):
        with self._lock:
            return [e for e in self.events if e["event"] == kind]


def _wait_for(pred, timeout=15.0):
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return pred()


def test_replica_close_reports_wedged_thread():
    """Satellite contract: close() must never silently succeed on a
    thread that is still running — a wedged replica returns False so
    callers can tell a hung shutdown from a clean one."""
    from cyclegan_tpu.serve.fleet.replica import ReplicaWorker

    eng = FakeEngine()
    eng.gate = threading.Event()  # run() blocks until released
    freed = []
    worker = ReplicaWorker(0, eng, on_free=freed.append)
    req = FleetRequest(np.zeros((32, 32, 3), np.float32), 32, "base", BATCH)
    worker.dispatch([req], "test")
    assert eng.entered.wait(timeout=10)
    assert worker.close(timeout=0.3) is False  # wedged in the engine
    assert worker.alive()
    eng.gate.set()  # release; the thread drains the flush and the _STOP
    assert _wait_for(lambda: not worker.alive())
    assert req.future.result(timeout=5)["fake"].shape == (32, 32, 3)


def test_fleet_recovers_from_injected_replica_crash():
    """replica_crash mid-flush: the monitor detects the dead thread,
    re-enqueues its in-flight requests, respawns the worker, and every
    future still resolves — no hung callers, no lost slots."""
    from cyclegan_tpu.resil import FaultInjector

    eng = FakeEngine(buckets=(1,))
    rec = _Recorder()
    inj = FaultInjector.from_spec("replica_crash@flush=1", telemetry=rec)
    fleet = FleetExecutor(
        eng,
        FleetConfig(n_replicas=1, max_batch=1, max_wait_ms=0.0,
                    health_poll_s=0.01),
        logger=rec, injector=inj)
    img = np.zeros((32, 32, 3), np.float32)
    futs = [fleet.submit(img, klass="batch") for _ in range(4)]
    for f in futs:
        assert f.result(timeout=30)["fake"].shape == (32, 32, 3)
    assert _wait_for(lambda: "fleet_recovery" in rec.kinds())
    summary = fleet.close()
    (down,) = rec.of("fleet_replica_down")
    assert down["reason"] == "crash" and down["inflight"] == 1
    (recov,) = rec.of("fleet_recovery")
    assert recov["respawned"] is True and recov["requeued"] == 1
    assert summary["recoveries"] == 1
    assert summary["requeued_requests"] == 1
    assert summary["crash_failed_requests"] == 0
    assert summary["circuits_open"] == 0
    assert summary["unjoined_replicas"] == []
    assert inj.pending() == []


def test_respawned_replica_rebinds_to_its_slots_engine():
    """Per-device fleet: with an explicit `engines` list, slot i runs
    engines[i % len(engines)] — and a respawn after a crash rebinds the
    slot to the SAME engine (same device), not to whichever engine is
    convenient. The device is fine when a replica thread dies; moving
    the slot to another chip would silently halve the fleet."""
    from cyclegan_tpu.resil import FaultInjector

    eng_a = FakeEngine(buckets=(1,))
    eng_b = FakeEngine(buckets=(1,))
    eng_a.device, eng_b.device = "cpu:0", "cpu:1"
    rec = _Recorder()
    inj = FaultInjector.from_spec("replica_crash@flush=1", telemetry=rec)
    fleet = FleetExecutor(
        eng_a,
        FleetConfig(n_replicas=3, max_batch=1, max_wait_ms=0.0,
                    health_poll_s=0.01),
        logger=rec, injector=inj, engines=[eng_a, eng_b])
    # Round-robin binding is visible in stats before any traffic.
    assert fleet.stats()["replica_devices"] == ["cpu:0", "cpu:1", "cpu:0"]
    before = list(fleet.replicas)
    img = np.zeros((32, 32, 3), np.float32)
    futs = [fleet.submit(img, klass="batch") for _ in range(6)]
    for f in futs:
        assert f.result(timeout=30)["fake"].shape == (32, 32, 3)
    assert _wait_for(lambda: "fleet_recovery" in rec.kinds())
    (recov,) = rec.of("fleet_recovery")
    assert recov["respawned"] is True
    slot = recov["replica"]
    # New worker object in the crashed slot, same engine identity.
    assert fleet.replicas[slot] is not before[slot]
    for i, worker in enumerate(fleet.replicas):
        assert worker.engine is fleet.engines[i % 2]
    assert fleet.stats()["replica_devices"] == ["cpu:0", "cpu:1", "cpu:0"]
    summary = fleet.close()
    assert summary["unjoined_replicas"] == []


def test_fleet_engines_must_share_bucket_grammar():
    """A replica whose engine lacks a bucket the dispatcher batches
    against would crash on its first flush — reject the mismatched
    engines list at construction instead."""
    eng = FakeEngine(buckets=(1,))
    other = FakeEngine(buckets=(1, 4))
    with pytest.raises(ValueError, match="bucket grammar"):
        FleetExecutor(eng, FleetConfig(n_replicas=2),
                      engines=[eng, other])


def test_crash_loop_burns_attempts_then_fails_future_typed():
    """A poison batch that kills its replica every time must not crash-
    loop forever: after max_request_attempts dispatches the request
    fails with ReplicaCrashed (typed, catchable) instead of hanging."""
    from cyclegan_tpu.resil import FaultInjector

    eng = FakeEngine(buckets=(1,))
    rec = _Recorder()
    inj = FaultInjector.from_spec("replica_crash@flush=0x10", telemetry=rec)
    fleet = FleetExecutor(
        eng,
        FleetConfig(n_replicas=1, max_batch=1, max_wait_ms=0.0,
                    health_poll_s=0.01, max_request_attempts=2,
                    max_replica_failures=5),
        logger=rec, injector=inj)
    fut = fleet.submit(np.zeros((32, 32, 3), np.float32), klass="batch")
    with pytest.raises(ReplicaCrashed):
        fut.result(timeout=30)
    assert _wait_for(lambda: fleet.stats()["crash_failed_requests"] >= 1)
    summary = fleet.close()
    assert summary["crash_failed_requests"] == 1
    assert summary["recoveries"] >= 2  # one per burned dispatch


def test_circuit_breaker_opens_and_close_drains_stranded_queue():
    """A replica dying on consecutive flushes is circuit-broken out of
    the fleet; with every circuit open, close() fails whatever is still
    queued with ReplicaCrashed instead of hanging the dispatcher."""
    from cyclegan_tpu.resil import FaultInjector

    eng = FakeEngine(buckets=(2,))
    rec = _Recorder()
    inj = FaultInjector.from_spec("replica_crash@flush=0x20", telemetry=rec)
    fleet = FleetExecutor(
        eng,
        FleetConfig(n_replicas=1, max_batch=2, max_wait_ms=0.0,
                    health_poll_s=0.01, max_replica_failures=2,
                    max_request_attempts=8),
        logger=rec, injector=inj)
    img = np.zeros((32, 32, 3), np.float32)
    futs = [fleet.submit(img, klass="batch") for _ in range(2)]
    assert _wait_for(
        lambda: any(e.get("circuit_open") for e in rec.of("fleet_recovery")))
    summary = fleet.close()
    for f in futs:
        with pytest.raises(ReplicaCrashed):
            f.result(timeout=5)
    assert summary["circuits_open"] == 1
    # Two recovery passes: the first respawned, the second hit the
    # consecutive-failure limit and opened the circuit instead.
    assert summary["recoveries"] == 2
    assert [e["respawned"] for e in rec.of("fleet_recovery")] == [True, False]
    assert fleet.stats()["circuits_open"] == 1


# -- multi-tenant serving ---------------------------------------------------


def test_tenant_spec_and_wiring_validation():
    with pytest.raises(ValueError, match="domain"):
        TenantSpec(domain="Bad Domain")
    with pytest.raises(ValueError, match="slo_ms"):
        TenantSpec(domain="maps", slo_ms=0)
    with pytest.raises(ValueError, match="shed_budget"):
        TenantSpec(domain="maps", shed_budget=1.5)
    with pytest.raises(ValueError, match="duplicate tenant"):
        FleetConfig(tenants=(TenantSpec(domain="maps"),
                             TenantSpec(domain="maps")))
    eng = FakeEngine()
    cfg = FleetConfig(tenants=(TenantSpec(domain="maps"),))
    # Every declared tenant needs its engine loaded up front ...
    with pytest.raises(ValueError, match="tenant_engines"):
        FleetExecutor(eng, cfg)
    # ... engines for undeclared tenants are refused ...
    with pytest.raises(ValueError, match="not declared"):
        FleetExecutor(eng, cfg, tenant_engines={
            "maps/base": FakeEngine(), "facades/base": FakeEngine()})
    # ... as are engines without any tenant declaration ...
    with pytest.raises(ValueError, match="cfg.tenants"):
        FleetExecutor(eng, FleetConfig(),
                      tenant_engines={"maps/base": FakeEngine()})
    # ... and a tenant engine speaking a different bucket grammar.
    with pytest.raises(ValueError, match="grammar"):
        FleetExecutor(eng, cfg, tenant_engines={
            "maps/base": FakeEngine(buckets=(1, 2))})


def test_fleet_routes_each_tenant_to_its_resident_engine():
    """Tenant routing: requests flush on the engine resident for their
    tenant key — never the primary — and the first declared tenant is
    the default for tenant-less submits."""
    primary, eng_a, eng_b = FakeEngine(), FakeEngine(), FakeEngine()
    cfg = FleetConfig(
        n_replicas=1, capacity=16, max_batch=1, max_wait_ms=0.0,
        tenants=(TenantSpec(domain="horse2zebra"),
                 TenantSpec(domain="apple2orange")))
    fleet = FleetExecutor(primary, cfg, tenant_engines={
        "horse2zebra/base": eng_a, "apple2orange/base": eng_b})
    img = np.zeros((32, 32, 3), np.float32)
    futs = [fleet.submit(img),  # default tenant = first declared
            fleet.submit(img, tenant="horse2zebra/base"),
            fleet.submit(img, tenant="apple2orange/base")]
    for f in futs:
        assert f.result(timeout=30)["fake"].shape == (32, 32, 3)
    with pytest.raises(KeyError, match="unknown tenant"):
        fleet.submit(img, tenant="maps/base")
    summary = fleet.close()
    assert sum(n for n, _, _ in eng_a.flushes) == 2
    assert sum(n for n, _, _ in eng_b.flushes) == 1
    assert primary.flushes == []
    tenants = summary["tenants"]
    assert tenants["horse2zebra/base"]["n_images"] == 2
    assert tenants["apple2orange/base"]["n_images"] == 1
    assert tenants["horse2zebra/base"]["domain"] == "horse2zebra"
    assert summary["tenant_swaps"] == 0
    assert summary["tenant_admission"]["horse2zebra/base"]["admitted"] == 2
    # A tenant-less fleet refuses tenant routing outright.
    plain = FleetExecutor(FakeEngine(), FleetConfig(n_replicas=1))
    with pytest.raises(KeyError, match="no\\s+tenants configured"):
        plain.submit(img, tenant="maps/base")
    plain.close()


def test_tenant_slo_tightens_but_never_loosens_the_deadline():
    img = np.zeros((32, 32, 3), np.float32)
    tight = FleetRequest(img, 32, "base", INTERACTIVE, now=0.0,
                         tenant="maps/base", slo_ms=5.0)
    assert tight.deadline == pytest.approx(0.005)
    loose = FleetRequest(img, 32, "base", INTERACTIVE, now=0.0,
                         tenant="maps/base",
                         slo_ms=10 * INTERACTIVE.deadline_ms)
    assert loose.deadline == pytest.approx(
        INTERACTIVE.deadline_ms / 1000.0)
    # The hedge twin carries the tenant key and the TIGHTENED deadline
    # verbatim (re-deriving from the class would silently loosen it).
    twin = tight.twin()
    assert twin.tenant == "maps/base"
    assert twin.deadline == tight.deadline


def test_shed_budget_protects_a_tenant_from_starvation():
    """Per-tenant shed budgets bound the victim scan: 0.25 over four
    admitted requests allows exactly ONE eviction, then the tenant
    stops being pickable and overload rejects arrivals at the door
    instead of starving the tenant to zero."""
    img = np.zeros((32, 32, 3), np.float32)
    adm = AdmissionController(capacity=4,
                              shed_budgets={"maps/base": 0.25})
    queued = [FleetRequest(img, 32, "base", BEST_EFFORT,
                           tenant="maps/base") for _ in range(4)]
    for r in queued:
        adm.offer(r)
    adm.offer(_req(INTERACTIVE))  # evicts one best_effort (in budget)
    assert sum(r.shed for r in queued) == 1
    with pytest.raises(ShedError):  # budget spent: arrival rejected
        adm.offer(_req(INTERACTIVE))
    assert sum(r.shed for r in queued) == 1  # still only one victim
    stats = adm.stats()
    assert stats["tenants"]["maps/base"] == {
        "admitted": 4, "shed": 1, "shed_budget": 0.25}
    adm.close()
    # Contrast: without a budget the same pressure evicts twice.
    unbudgeted = AdmissionController(capacity=4)
    queued2 = [FleetRequest(img, 32, "base", BEST_EFFORT,
                            tenant="maps/base") for _ in range(4)]
    for r in queued2:
        unbudgeted.offer(r)
    unbudgeted.offer(_req(INTERACTIVE))
    unbudgeted.offer(_req(INTERACTIVE))
    assert sum(r.shed for r in queued2) == 2
    unbudgeted.close()


def test_hot_swap_under_load_drops_nothing():
    """The acceptance pin: hot checkpoint swap with a loaded queue.
    The in-flight flush resolves on the OLD engine (it keeps the
    reference it was dispatched with), queued work picks up the NEW
    engine at dispatch, and every submitted request resolves — zero
    dropped."""
    old, new, primary = FakeEngine(), FakeEngine(), FakeEngine()
    old.gate = threading.Event()
    rec = _Recorder()
    cfg = FleetConfig(
        n_replicas=1, capacity=64, max_batch=4, max_wait_ms=0.0,
        tenants=(TenantSpec(domain="horse2zebra", slo_ms=60000.0),))
    fleet = FleetExecutor(primary, cfg, logger=rec,
                          tenant_engines={"horse2zebra/base": old})
    img = np.zeros((32, 32, 3), np.float32)
    futs = [fleet.submit(img, klass="batch") for _ in range(20)]
    assert old.entered.wait(timeout=10)  # a flush is in flight on OLD
    returned = fleet.swap_tenant("horse2zebra/base", new)
    assert returned is old  # caller gets the old engine back to release
    with pytest.raises(KeyError, match="unknown tenant"):
        fleet.swap_tenant("maps/base", new)
    with pytest.raises(ValueError, match="grammar"):
        fleet.swap_tenant("horse2zebra/base", FakeEngine(buckets=(1, 2)))
    snap = fleet.stats()
    assert snap["tenant_swaps"] == 1
    assert "horse2zebra/base" in snap["tenants"]
    old.gate.set()
    for f in futs:  # ZERO dropped: every future resolves with a result
        assert f.result(timeout=30)["fake"].shape == (32, 32, 3)
    summary = fleet.close()
    n_old = sum(n for n, _, _ in old.flushes)
    n_new = sum(n for n, _, _ in new.flushes)
    assert n_old + n_new == 20
    assert n_old >= 1  # in-flight work finished on the old engine
    assert n_new >= 1  # queued work crossed over to the new engine
    assert summary["shed"] == {}
    tenants = summary["tenants"]
    assert tenants["horse2zebra/base"]["n_images"] == 20
    assert tenants["horse2zebra/base"]["slo_misses"] == 0
    assert summary["tenant_swaps"] == 1
    (ev,) = rec.of("fleet_tenant_swap")
    assert ev["tenant"] == "horse2zebra/base"
    assert ev["queue_depth"] >= 1  # swapped under genuine load
